"""Flight recorder (telemetry/journal.py): ring semantics, lineage
reconstruction, replica-deterministic /debugz, auto-dump triggers, and
the zero-cost-disabled / zero-readback contracts.

The r14 acceptance bar: ``journal.lineage(doc, seq)`` reconstructs a
sampled op's full stage path submit → admit → ticket → append → stage →
dispatch → commit → broadcast end-to-end over a real websocket, and a
chaos run with an injected crash auto-dumps a file carrying that op's
lineage plus the injection event — with ZERO new device readbacks and
nothing allocated while disabled.
"""

import json
import socket
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from fluidframework_tpu.models.shared_string import SharedString
from fluidframework_tpu.protocol.constants import (
    F_ARG,
    F_LEN,
    F_REF,
    F_SEQ,
    F_TYPE,
    OP_INSERT,
    OP_WIDTH,
)
from fluidframework_tpu.protocol.opframe import OpFrame, SeqFrame
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.device_backend import DeviceFleetBackend
from fluidframework_tpu.service.pipeline import PipelineFluidService
from fluidframework_tpu.telemetry import journal, metrics
from fluidframework_tpu.testing import faults

MINT = 1 << 14  # shared_string._MINT_STRIDE


@pytest.fixture(autouse=True)
def _clean_journal():
    journal.enable()
    journal.reset()
    journal.JOURNAL.dump_dir = None
    faults.reset()
    metrics.REGISTRY.reset()
    yield
    faults.reset()
    journal.enable()
    journal.reset()
    journal.JOURNAL.dump_dir = None
    metrics.REGISTRY.reset()


# ---------------------------------------------------------------------------
# Ring primitives


def test_ring_bound_eviction_order():
    """The ring is bounded and evicts OLDEST-first: after overflow the
    surviving ids are the contiguous tail, and the eviction count is
    visible (seen - len)."""
    j = journal.Journal(capacity=16)
    for i in range(21):
        j.record("pressure", score=i)
    evs = j.events()
    assert len(evs) == 16
    assert [e.eid for e in evs] == list(range(5, 21))
    assert j.seen == 21 and j.evicted == 5
    assert "evicted=5" in j.render().splitlines()[0]


def test_unknown_event_kind_raises():
    with pytest.raises(ValueError):
        journal.JOURNAL.record("not.a.kind")


def test_debugz_render_is_replica_deterministic():
    """Two replicas observing the SAME events render byte-equal /debugz
    text: event ids are logical, wall timestamps are excluded (they live
    only in the file-dump form), details render in sorted order."""
    a, b = journal.Journal(capacity=64), journal.Journal(capacity=64)
    for j, delay in ((a, 0.0), (b, 0.02)):
        j.record("frame.submit", doc="d", client=3, csn=1, csn_hi=4, n=4)
        if delay:
            time.sleep(delay)  # wall clocks diverge; renders must not
        j.record(
            "frame.ticket", doc="d", seq=10, seq_hi=13, csn=1, csn_hi=4,
            client=3,
        )
        j.record("device.stage", spans=(("d", 10, 13),), rows=4)
        j.record("pressure", ring_frac=0.5, queue_frac=0.25, feed_lag_ms=1.5)
    assert a.render() == b.render()
    # The dump form DOES carry timestamps (the post-mortem needs them);
    # the deterministic render never does.
    assert '"ts":' in a.dump_payload("x")
    for ev in a.events():
        assert str(round(ev.ts, 6)) not in a.render()


# ---------------------------------------------------------------------------
# Lineage reconstruction (pipeline level)


def _one_frame(conn, svc, doc, k=3, c0=1):
    origs = [conn.conn_no * MINT + c0 + j for j in range(k)]
    return OpFrame.build(
        "s", ["ins"] * k, [0] * k, origs, ["x"] * k, csn0=c0,
        ref=svc.doc_head(doc),
    )


LINEAGE_PATH = {
    "frame.submit", "admission.admit", "frame.ticket", "log.append",
    "device.stage", "device.dispatch", "device.commit", "broadcast",
}


def test_lineage_device_committed_op():
    """The full path for an op that rode the device: submit → admit →
    ticket → append → stage → dispatch → commit → broadcast, in record
    order."""
    svc = PipelineFluidService(n_partitions=2)
    conn = svc.connect("lin-doc")
    head = svc.doc_head("lin-doc")
    conn.submit_frame(_one_frame(conn, svc, "lin-doc"))
    svc.pump()
    svc.flush_device()
    lin = journal.lineage("lin-doc", head + 2)  # mid-frame op
    kinds = [e.kind for e in lin]
    assert LINEAGE_PATH <= set(kinds), kinds
    # Record order is monotone and the pre-sequencing half precedes the
    # ticket that resolved the identity join.
    assert [e.eid for e in lin] == sorted(e.eid for e in lin)
    assert kinds.index("frame.submit") < kinds.index("frame.ticket")
    assert kinds.index("device.stage") < kinds.index("device.commit")


def test_lineage_dup_nacked_op():
    """A replayed frame dropped whole by deli's dedup leaves a
    ``frame.nack(reason=dup)`` entry correlated by (client, csn) — the
    resubmit's death is visible in the op's lineage, not silent."""
    svc = PipelineFluidService(n_partitions=2)
    conn = svc.connect("dup-doc")
    head = svc.doc_head("dup-doc")
    frame = _one_frame(conn, svc, "dup-doc")
    conn.submit_frame(frame)
    conn.submit_frame(frame)  # same csn range: whole-frame duplicate
    svc.pump()
    svc.flush_device()
    lin = journal.lineage("dup-doc", head + 1)
    nacks = [e for e in lin if e.kind == "frame.nack"]
    assert len(nacks) == 1
    assert dict(nacks[0].detail)["reason"] == "dup"
    assert LINEAGE_PATH <= {e.kind for e in lin}


# ---------------------------------------------------------------------------
# Zero cost disabled / zero readbacks enabled


def test_zero_alloc_when_disabled(monkeypatch):
    """Disabled, the journal allocates NOTHING: every producer site is
    one predicate; the counting shim pins that no record call reaches
    the ring through a full pipeline workload."""
    calls = []
    orig = journal.Journal.record

    def counting(self, kind, **kw):
        calls.append(kind)
        return orig(self, kind, **kw)

    monkeypatch.setattr(journal.Journal, "record", counting)
    journal.disable()
    svc = PipelineFluidService(n_partitions=2)
    conn = svc.connect("off-doc")
    conn.submit_frame(_one_frame(conn, svc, "off-doc"))
    svc.pump()
    svc.flush_device()
    assert calls == []
    assert journal.JOURNAL.seen == 0
    journal.enable()
    conn.submit_frame(_one_frame(conn, svc, "off-doc", c0=4))
    svc.pump()
    svc.flush_device()
    assert calls, "re-enabled journal must record again"


def test_journal_adds_zero_device_readbacks(monkeypatch):
    """The zero-readback contract: journal-on performs EXACTLY the same
    device→host transfers as journal-off — the commit events consume the
    pump's existing one-boxcar-stale scan, never their own pull."""
    from fluidframework_tpu.parallel import fleet as fleet_mod
    from fluidframework_tpu.service import device_backend as db_mod

    def run() -> int:
        be = DeviceFleetBackend(
            capacity=128, max_batch=1 << 20, pump_mode=True
        )
        ar = np.arange(4, dtype=np.int32)
        calls = []
        real = np.asarray

        class _CountingNp:
            def __getattr__(self, name):
                return getattr(np, name)

            @staticmethod
            def asarray(*a, **kw):
                calls.append(1)
                return real(*a, **kw)

            @staticmethod
            def array(*a, **kw):
                calls.append(1)
                return np.array(*a, **kw)

        monkeypatch.setattr(fleet_mod, "np", _CountingNp())
        monkeypatch.setattr(db_mod, "np", _CountingNp())
        try:
            for r in range(3):
                for i in range(4):
                    rows = np.zeros((4, OP_WIDTH), np.int32)
                    rows[:, F_TYPE] = OP_INSERT
                    rows[:, F_LEN] = 1
                    rows[:, F_SEQ] = r * 4 + 1 + ar
                    rows[:, F_REF] = r * 4
                    rows[:, F_ARG] = r * 4 + 1 + ar
                    be.enqueue_frame(
                        f"d{i}", SeqFrame("s", 0, 1, rows, (), 0.0)
                    )
                be.flush()
            be.pump_drain()
        finally:
            monkeypatch.setattr(fleet_mod, "np", np)
            monkeypatch.setattr(db_mod, "np", np)
        return len(calls)

    journal.disable()
    off = run()
    journal.enable()
    journal.reset()
    on = run()
    assert on == off, f"journal added readbacks: on={on} off={off}"
    assert journal.JOURNAL.seen > 0


# ---------------------------------------------------------------------------
# Auto-dump triggers


def test_chaos_crash_auto_dumps_lineage_and_injection(tmp_path):
    """The acceptance cell: an injected crash at the dispatch boundary
    lands an auto-dump file carrying (a) the injection event and (b) the
    in-flight op's lineage entries — 'bit-exact assertion failed'
    becomes a diagnosable event stream."""
    svc = PipelineFluidService(n_partitions=2)
    conn = svc.connect("cr-doc")
    head = svc.doc_head("cr-doc")
    journal.configure(dump_dir=str(tmp_path))
    faults.arm("pump.dispatch", faults.CrashAt("after"))
    try:
        conn.submit_frame(_one_frame(conn, svc, "cr-doc"))
    except faults.InjectedFault:
        pass  # the harness plays the restart supervisor
    faults.disarm()
    svc.pump()
    svc.flush_device()
    files = sorted(tmp_path.glob("journal-*.json"))
    assert files, "fatal dispatch crash must auto-dump"
    doc = json.loads(files[0].read_text())
    assert doc["reason"] == "pump.dispatch-fatal"
    kinds = [e["kind"] for e in doc["events"]]
    assert "fault.injected" in kinds
    inj = next(e for e in doc["events"] if e["kind"] == "fault.injected")
    assert inj["detail"] == {"site": "pump.dispatch", "fault": "crash_after"}
    # The crashed op's lineage up to the crash is in the dump: its
    # submit, ticket, append, and the staged boxcar covering its seqs.
    assert "frame.ticket" in kinds and "device.stage" in kinds
    staged = next(e for e in doc["events"] if e["kind"] == "device.stage")
    assert any(d == "cr-doc" and lo <= head + 1 <= hi
               for d, lo, hi in staged["spans"])
    # And a dumps counter moved — never a silent file write.
    assert metrics.REGISTRY.get("journal_dumps_total").value(
        reason="pump.dispatch-fatal"
    ) == 1


def test_err_lane_trip_auto_dumps(tmp_path):
    """An err-lane trip (channel over device capacity) journals the
    channel and auto-dumps."""
    svc = PipelineFluidService(
        n_partitions=2, device_capacity=8, device_max_capacity=8
    )
    journal.configure(dump_dir=str(tmp_path))
    conn = svc.connect("err-doc")
    k = 24  # blows past the 8-slot top tier
    frame = OpFrame.build(
        "s", ["ins"] * k, [0] * k,
        [conn.conn_no * MINT + 1 + j for j in range(k)], ["x"] * k,
        csn0=1, ref=svc.doc_head("err-doc"),
    )
    conn.submit_frame(frame)
    svc.pump()
    svc.flush_device()
    evs = [e for e in journal.JOURNAL.events() if e.kind == "device.err"]
    assert evs and evs[0].doc == "err-doc"
    files = sorted(tmp_path.glob("journal-*err_lane*.json"))
    assert files, "err-lane trip must auto-dump"


def test_dump_budget_bounds_files(tmp_path):
    journal.configure(dump_dir=str(tmp_path), max_dumps=2)
    for i in range(5):
        journal.auto_dump("err_lane")
    assert len(list(tmp_path.glob("journal-*.json"))) == 2


def test_failed_dump_is_counted_and_absorbed(tmp_path):
    """The ``journal.dump`` site's contract: a failed dump write is
    counted (retry_attempts_total{journal.dump,fallback}) and absorbed —
    never raised into the serving path — and the ring still holds the
    events for /debugz."""
    journal.configure(dump_dir=str(tmp_path))
    journal.record("device.err", doc="d", addr="s")
    faults.arm("journal.dump", faults.FailN(1))
    assert journal.auto_dump("err_lane") is None
    faults.disarm()
    c = metrics.REGISTRY.get("retry_attempts_total")
    assert c.value(site="journal.dump", outcome="fallback") == 1
    assert list(tmp_path.glob("journal-*.json")) == []
    assert "device.err" in journal.render()
    # Budget not burned pointlessly on top of the failure is not
    # promised; what IS promised: the next dump attempt still works.
    assert journal.auto_dump("err_lane") is not None


def test_retry_exhaustion_auto_dumps(tmp_path):
    """An exhausted retry budget at any site fires the auto-dump."""
    from fluidframework_tpu.service.retry import RetryPolicy, call_with_retry

    journal.configure(dump_dir=str(tmp_path))

    def always():
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        call_with_retry(
            "queue.send", always, policy=RetryPolicy(max_attempts=2),
            sleep=lambda _d: None,
        )
    files = list(tmp_path.glob("journal-*.json"))
    assert len(files) == 1
    doc = json.loads(files[0].read_text())
    assert doc["reason"] == "queue.send-exhausted"
    kinds = [e["kind"] for e in doc["events"]]
    assert kinds.count("retry.outcome") >= 2  # the retry + the exhaustion


# ---------------------------------------------------------------------------
# /debugz surfaces


def test_debugz_over_network_server_and_shed_exemption():
    """GET /debugz on the front door returns the deterministic journal
    render, and stays reachable at REFUSE_CONNECTIONS exactly like
    /metrics (the post-mortem surface must survive the overload it
    documents) while ordinary reads are refused."""
    from fluidframework_tpu.service.admission import Tier
    from fluidframework_tpu.service.network_server import FluidNetworkServer

    svc = PipelineFluidService(n_partitions=2)
    conn = svc.connect("dz-doc")
    conn.submit_frame(_one_frame(conn, svc, "dz-doc"))
    svc.pump()
    srv = FluidNetworkServer(service=svc)
    srv.start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debugz", timeout=5
        ).read().decode()
        assert body.startswith("# flight-recorder")
        assert "frame.ticket doc=dz-doc" in body
        assert body == journal.render()  # replica-deterministic bytes
        svc.overload.force(Tier.REFUSE_CONNECTIONS)
        body2 = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debugz", timeout=5
        ).read().decode()
        assert body2.startswith("# flight-recorder")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/deltas/dz-doc", timeout=5
            )
        svc.overload.force(None)
    finally:
        srv.stop()


def test_debugz_on_store_node():
    from fluidframework_tpu.service.store_server import StoreServer

    journal.record("log.append", doc="sn-doc", seq=7)
    node = StoreServer(port=0, n_partitions=2).serve_background()
    try:
        with socket.create_connection((node.host, node.port), timeout=5) as s:
            s.sendall(b"GET /debugz HTTP/1.1\r\nHost: x\r\n\r\n")
            buf = b""
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                buf += chunk
        head, _, body = buf.partition(b"\r\n\r\n")
        assert head.startswith(b"HTTP/1.1 200 OK")
        text = body.decode()
        assert text.startswith("# flight-recorder")
        assert "log.append doc=sn-doc seq=7" in text
    finally:
        node.close()


# ---------------------------------------------------------------------------
# The acceptance bar: lineage end-to-end over a real websocket


def test_lineage_end_to_end_over_real_websocket():
    """A sampled op submitted by a real websocket client reconstructs
    its full stage path from the journal — and the /debugz surface
    serves the same ring the lineage came from."""
    from fluidframework_tpu.drivers.network_driver import NetworkFluidService
    from fluidframework_tpu.service.network_server import FluidNetworkServer

    svc = PipelineFluidService(n_partitions=2, messages_per_trace=1)
    srv = FluidNetworkServer(service=svc)
    srv.start()
    try:
        rts = [
            ContainerRuntime(
                NetworkFluidService("127.0.0.1", srv.port), "ws-doc",
                channels=(SharedString("s"),),
            )
            for _ in range(2)
        ]
        for i, rt in enumerate(rts):
            ch = rt.get_channel("s")
            for j in range(4):
                ch.insert_text(0, chr(97 + (i * 4 + j) % 26))
        deadline = time.monotonic() + 10
        for rt in rts:
            rt.flush()
        quiet = 0
        while time.monotonic() < deadline and quiet < 3:
            if any(rt.process_incoming() for rt in rts):
                quiet = 0
            else:
                quiet += 1
                time.sleep(0.02)
        svc.flush_device()
        assert srv.frames_received >= 2, "frame wire not taken"
        texts = {rt.get_channel("s").get_text() for rt in rts}
        assert len(texts) == 1
        # Pick a sequenced op off a ticket event and reconstruct it.
        tickets = [
            e for e in journal.JOURNAL.events()
            if e.kind == "frame.ticket" and e.doc == "ws-doc"
        ]
        assert tickets
        seq = tickets[-1].seq_hi
        kinds = {e.kind for e in journal.lineage("ws-doc", seq)}
        assert LINEAGE_PATH <= kinds, kinds
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debugz", timeout=5
        ).read().decode()
        assert f"frame.ticket doc=ws-doc" in body
        for rt in rts:
            rt.disconnect()
    finally:
        srv.stop()
