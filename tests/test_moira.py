"""Moira — changeset streaming to an external index (VERDICT r3 #5).

Reference ``lambdas/src/moira/lambda.ts:19``: the service's only
feed-external-consumers stage. The contract under test: at-least-once
delivery into a guid-idempotent sink, checkpointed resume after a crash,
and retry (without losing pipeline liveness) across sink outages — the
index always converges gap-free and dup-free."""

from fluidframework_tpu.models.shared_string import SharedString
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.moira import (
    MaterializedIndexSink,
    MoiraLambda,
)
from fluidframework_tpu.service.lambdas import stored_message
from fluidframework_tpu.service.pipeline import PipelineFluidService


def drain(runtimes):
    for _ in range(6):
        for r in runtimes:
            r.flush()
            r.process_incoming()


def _author(svc, n_ops: int, doc="doc"):
    a = ContainerRuntime(svc, doc, channels=(SharedString("s"),))
    for i in range(n_ops):
        a.get_channel("s").insert_text(0, f"w{i} ")
        if i % 3 == 2:
            drain([a])
    drain([a])
    return a


def _indexed_seqs(sink, doc="doc"):
    seqs = sink.doc_seqs(doc)
    assert seqs == sorted(seqs), "index out of order"
    assert len(seqs) == len(set(seqs)), "duplicate seq indexed"
    return seqs


def test_moira_streams_every_content_op():
    sink = MaterializedIndexSink()
    svc = PipelineFluidService(
        n_partitions=2, device_backend=False, index_sink=sink
    )
    _author(svc, 9)
    seqs = _indexed_seqs(sink)
    # Every content-bearing sequenced op is indexed exactly once, in
    # order (joins/noops are not changesets).
    ops = [
        s for s, m in sorted(
            (k, stored_message(v))
            for k, v in svc.ops_store["doc"].items()
        )
        if m.type == 1 and m.contents is not None
    ]
    assert seqs == ops
    assert sink.duplicate_posts == 0


def test_moira_kill_restart_converges_without_gaps_or_dups():
    sink = MaterializedIndexSink()
    svc = PipelineFluidService(
        n_partitions=2, device_backend=False, index_sink=sink,
        checkpoint_every=3,
    )
    # Author one op per drain: per-op deltas records keep the moira
    # checkpoint strictly inside the record stream, so the crash below
    # has a genuine replay window. (Multi-op flushes ride the frame
    # wire as ONE record — checkpoint_every=3 could then land exactly
    # on the log head and the replay-absorption proof would be vacuous.)
    a = ContainerRuntime(svc, "doc", channels=(SharedString("s"),))
    for i in range(6):
        a.get_channel("s").insert_text(0, f"w{i} ")
        drain([a])
    before = _indexed_seqs(sink)
    assert before, "stream must have started"
    # Kill the streamer; its checkpoint may trail the sink (records
    # posted but not yet checkpointed) — the restart replays that window.
    svc.crash_moira(checkpoint_every=3)
    for i in range(6, 12):
        a.get_channel("s").insert_text(0, f"w{i} ")
        drain([a])
    after = _indexed_seqs(sink)
    ops = [
        s for s, m in sorted(
            (k, stored_message(v))
            for k, v in svc.ops_store["doc"].items()
        )
        if m.type == 1 and m.contents is not None
    ]
    assert after == ops, "index must converge gap-free after restart"
    # The crash window genuinely replayed input — absorption, not luck:
    # either the guid upsert swallowed a duplicate post or the acked-seq
    # watermark dropped it pre-post.
    restarted = svc._moira._lambdas
    skipped = sum(l.skipped_replays for l in restarted.values())
    # checkpoint_every=3 with 6 pre-crash commits guarantees the restart
    # re-reads at least one already-indexed delta, so at least one replay
    # MUST have been absorbed (guid upsert or acked-seq watermark) — if
    # neither fired, the crash window silently vanished.
    assert sink.duplicate_posts + skipped > 0
    assert len(after) > len(before)


def test_moira_sink_outage_retries_without_stalling_pipeline():
    sink = MaterializedIndexSink(fail_every=5)  # every 5th commit errors
    svc = PipelineFluidService(
        n_partitions=1, device_backend=False, index_sink=sink,
        checkpoint_every=2,
    )
    a = _author(svc, 10)
    # Outages raised mid-pump; later pumps retried from the offset.
    for _ in range(8):
        svc.pump()
    ops = [
        s for s, m in sorted(
            (k, stored_message(v))
            for k, v in svc.ops_store["doc"].items()
        )
        if m.type == 1 and m.contents is not None
    ]
    assert _indexed_seqs(sink) == ops
    assert sink.commit_calls > len(ops), "retries must have happened"
    # The document itself kept serving during the outage.
    assert "w9" in a.get_channel("s").get_text()


def test_moira_restart_resumes_from_checkpoint_not_zero():
    """Restore must resume from the acked watermark: a fresh lambda with
    the checkpointed state skips everything below it without consulting
    the sink."""
    sink = MaterializedIndexSink()
    lam = MoiraLambda(sink)
    from fluidframework_tpu.protocol.types import (
        MessageType,
        SequencedDocumentMessage,
    )

    def seq_msg(n):
        return {
            "t": "seq",
            "msg": SequencedDocumentMessage(
                client_id=1, sequence_number=n, client_sequence_number=n,
                reference_sequence_number=n - 1,
                minimum_sequence_number=0, type=MessageType.OPERATION,
                contents={"op": n},
            ),
        }

    for n in (1, 2, 3):
        lam.handler("d", seq_msg(n))
    assert sink.doc_seqs("d") == [1, 2, 3]
    lam2 = MoiraLambda(sink, state=lam.state())
    for n in (2, 3, 4):  # replayed tail + one new record
        lam2.handler("d", seq_msg(n))
    assert sink.doc_seqs("d") == [1, 2, 3, 4]
    assert lam2.skipped_replays == 2
    assert sink.duplicate_posts == 0
