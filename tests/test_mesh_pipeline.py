"""Mesh-sharded serving fleet (VERDICT r4 do #3): the PIPELINE path —
deli partitions -> TpuDeliLambda -> DocFleet — with the document axis
sharded over the 8-device virtual mesh, parity-checked against the
single-device fleet.

Reference deployment shape: per-partition lambdas shard documents across
hosts (``lambdas-driver/src/document-router/documentLambda.ts:20``);
here the shard target is a ``jax.sharding.Mesh`` docs axis
(SURVEY.md:13-15).
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from fluidframework_tpu.protocol.opframe import OpFrame
from fluidframework_tpu.service.pipeline import PipelineFluidService

MINT = 1 << 14


def _mesh() -> Mesh:
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    return Mesh(np.array(devs[:8]), ("docs",))


def _drive(svc, n_docs=24, rounds=2, k=4):
    """Connect one writer per doc, pump k-op frames per round; returns
    expected text per doc (inserts at 0 -> reversed alphabet run)."""
    conns = {}
    docs = [f"m{i}" for i in range(n_docs)]
    for d in docs:
        conns[d] = svc.connect(d)
    total = {d: 0 for d in docs}
    for _r in range(rounds):
        for d in docs:
            conn = conns[d]
            o0 = total[d]
            f = OpFrame.build(
                "s", ["ins"] * k, [0] * k,
                [conn.conn_no * MINT + o0 + 1 + i for i in range(k)],
                [chr(97 + (o0 + 1 + i) % 26) for i in range(k)],
                csn0=o0 + 1, ref=svc.doc_head(d),
            )
            conn.submit_frame(f)
            total[d] += k
    svc.flush_device()
    return {
        d: "".join(chr(97 + (o % 26)) for o in range(total[d], 0, -1))
        for d in docs
    }


def test_pipeline_parity_mesh_vs_single_device():
    mesh = _mesh()
    svc_mesh = PipelineFluidService(n_partitions=2, device_mesh=mesh)
    svc_one = PipelineFluidService(n_partitions=2)
    want_mesh = _drive(svc_mesh)
    want_one = _drive(svc_one)
    assert want_mesh == want_one
    for d, want in want_mesh.items():
        assert svc_mesh.device_text(d, "s") == want
        assert svc_one.device_text(d, "s") == want
        sm = svc_mesh.device.channel_summary(d, "s")
        so = svc_one.device.channel_summary(d, "s")
        assert sm["count"] == so["count"]
        assert sm["lanes"] == so["lanes"]
    # The fleet state genuinely spans the mesh, not one device.
    pool = next(iter(svc_mesh.device.fleet.pools.values()))
    devices = {s.device for s in pool.state.count.addressable_shards}
    assert len(devices) == 8, devices
    assert svc_mesh.device.stats()["docs_with_errors"] == 0


def test_mesh_fleet_rides_pallas_engine():
    """VERDICT r5 Weak #4: the mesh fleet used to force kernel="xla", so
    the demonstrated deployment shape and the measured perf path ran
    DIFFERENT engines. Now the fused Pallas kernels run per shard under
    shard_map (the DocShard pattern): pipeline parity vs the XLA fleet,
    on the real sharded product path."""
    mesh = _mesh()
    svc_p = PipelineFluidService(
        n_partitions=2, device_mesh=mesh, device_kernel="pallas",
    )
    svc_x = PipelineFluidService(n_partitions=2, device_mesh=mesh)
    assert svc_p.device.fleet.kernel == "pallas"
    want_p = _drive(svc_p, n_docs=16)
    want_x = _drive(svc_x, n_docs=16)
    assert want_p == want_x
    for d, want in want_p.items():
        assert svc_p.device_text(d, "s") == want
        sp = svc_p.device.channel_summary(d, "s")
        sx = svc_x.device.channel_summary(d, "s")
        assert sp["count"] == sx["count"]
        assert sp["lanes"] == sx["lanes"]
    pool = next(iter(svc_p.device.fleet.pools.values()))
    devices = {s.device for s in pool.state.count.addressable_shards}
    assert len(devices) == 8, devices
    assert svc_p.device.stats()["docs_with_errors"] == 0


def test_mesh_fleet_promotion_keeps_sharding_and_state():
    """Docs that outgrow the base tier promote into a bigger pool that is
    ALSO mesh-sharded, with no text corruption."""
    mesh = _mesh()
    svc = PipelineFluidService(
        n_partitions=1, device_mesh=mesh, device_capacity=16,
    )
    conn = svc.connect("grow")
    csn = 0
    for _r in range(6):
        k = 4
        f = OpFrame.build(
            "s", ["ins"] * k, [0] * k,
            [conn.conn_no * MINT + csn + 1 + i for i in range(k)],
            ["x"] * k, csn0=csn + 1, ref=svc.doc_head("grow"),
        )
        conn.submit_frame(f)
        csn += k
        svc.flush_device()
    assert svc.device_text("grow", "s") == "x" * csn
    fleet = svc.device.fleet
    idx = svc.device._index[("grow", "s")]
    cap, _slot = fleet.placement[idx]
    assert cap > 16, "doc should have promoted past the base tier"
    big = fleet.pools[cap]
    devices = {s.device for s in big.state.count.addressable_shards}
    assert len(devices) == 8
    assert svc.device.stats()["docs_with_errors"] == 0
