"""SharedDirectory, Ink, SharedSummaryBlock, IdCompressor.

Reference coverage: packages/dds/map SharedDirectory (directory.ts),
packages/dds/ink, packages/dds/shared-summary-block, and
packages/dds/tree/src/id-compressor (SURVEY.md §2.2) — multi-client
convergence through the in-proc ordering service (§4 layer 2).
"""

import numpy as np
import pytest

from fluidframework_tpu.models.id_compressor import IdCompressor
from fluidframework_tpu.models.ink import Ink
from fluidframework_tpu.models.shared_directory import SharedDirectory
from fluidframework_tpu.models.summary_block import SharedSummaryBlock
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.local_server import LocalFluidService


def make(n, channels_fn):
    svc = LocalFluidService()
    return svc, [
        ContainerRuntime(svc, "doc", channels=channels_fn()) for _ in range(n)
    ]


def drain(rts):
    for rt in rts:
        rt.flush()
    while any(rt.process_incoming() for rt in rts):
        pass


class TestSharedDirectory:
    def test_root_and_nested_keys_converge(self):
        svc, (a, b) = make(2, lambda: (SharedDirectory("d"),))
        da, db = a.get_channel("d"), b.get_channel("d")
        da.set("top", 1)
        wa = da.create_subdirectory("ws")
        wa.set("x", 10)
        wa.create_subdirectory("deep").set("y", 20)
        drain([a, b])
        assert db.get("top") == 1
        wb = db.get_subdirectory("ws")
        assert wb.get("x") == 10
        assert wb.get_subdirectory("deep").get("y") == 20
        assert [n for n, _ in db.root.subdirectories()] == ["ws"]

    def test_same_key_lww_and_local_pending_wins(self):
        svc, (a, b) = make(2, lambda: (SharedDirectory("d"),))
        da, db = a.get_channel("d"), b.get_channel("d")
        da.create_subdirectory("s")
        drain([a, b])
        da.get_subdirectory("s").set("k", "a")
        db.get_subdirectory("s").set("k", "b")
        drain([a, b])
        assert da.get_subdirectory("s").get("k") == db.get_subdirectory("s").get("k")

    def test_rmdir_drops_subtree_everywhere(self):
        svc, (a, b) = make(2, lambda: (SharedDirectory("d"),))
        da, db = a.get_channel("d"), b.get_channel("d")
        da.create_subdirectory("gone").set("k", 1)
        da.get_subdirectory("gone").create_subdirectory("below").set("z", 2)
        drain([a, b])
        assert db.get_subdirectory("gone").get_subdirectory("below").get("z") == 2
        db.root.delete_subdirectory("gone")
        drain([a, b])
        assert da.get_subdirectory("gone") is None
        assert db.get_subdirectory("gone") is None

    def test_set_under_concurrently_deleted_subtree_dropped(self):
        svc, (a, b) = make(2, lambda: (SharedDirectory("d"),))
        da, db = a.get_channel("d"), b.get_channel("d")
        da.create_subdirectory("s")
        drain([a, b])
        # Concurrent: a writes under /s while b deletes /s.
        da.get_subdirectory("s").set("k", 1)
        db.root.delete_subdirectory("s")
        drain([a, b])
        assert da.get_subdirectory("s") is None
        assert db.get_subdirectory("s") is None

    def test_clear_total_order_semantics(self):
        # Case 1: set sequences before clear -> the clear wipes it on every
        # replica (including the setter, whose set was acked first).
        svc, (a, b) = make(2, lambda: (SharedDirectory("d"),))
        da, db = a.get_channel("d"), b.get_channel("d")
        da.set("stale", 1)
        drain([a, b])
        da.set("mine", 2)
        db.root.clear()
        drain([a, b])  # a flushes first: set @ N, clear @ N+1
        assert not da.has("mine") and not db.has("mine")
        assert not da.has("stale") and not db.has("stale")

        # Case 2: clear sequences before set -> the set survives everywhere.
        db.root.clear()
        da.set("keep", 3)
        for rt in (b, a):  # b flushes first: clear @ M, set @ M+1
            rt.flush()
        drain([a, b])
        assert da.get("keep") == 3 and db.get("keep") == 3

    def test_summary_roundtrip(self):
        svc, (a,) = make(1, lambda: (SharedDirectory("d"),))
        d = a.get_channel("d")
        d.set("k", 1)
        d.create_subdirectory("s").set("x", [1, 2])
        drain([a])
        a.submit_summary()
        drain([a])
        b = ContainerRuntime(svc, "doc", channels=(SharedDirectory("d"),))
        assert b.get_channel("d").get("k") == 1
        assert b.get_channel("d").get_subdirectory("s").get("x") == [1, 2]


class TestInk:
    def test_strokes_converge(self):
        svc, (a, b) = make(2, lambda: (Ink("ink"),))
        ia, ib = a.get_channel("ink"), b.get_channel("ink")
        sa = ia.create_stroke({"color": "red"})
        ia.append_points(sa.id, [[0, 0, 0.0, 1.0], [1, 1, 0.1, 1.0]])
        sb = ib.create_stroke({"color": "blue"})
        ib.append_points(sb.id, [[5, 5, 0.0, 0.5]])
        drain([a, b])
        assert [s.id for s in ia.strokes()] == [s.id for s in ib.strokes()]
        assert ib.get_stroke(sa.id).points.shape == (2, 4)
        assert ia.get_stroke(sb.id).pen == {"color": "blue"}
        np.testing.assert_array_equal(
            ia.get_stroke(sa.id).points, ib.get_stroke(sa.id).points
        )

    def test_incremental_appends_in_order(self):
        svc, (a, b) = make(2, lambda: (Ink("ink"),))
        ia, ib = a.get_channel("ink"), b.get_channel("ink")
        s = ia.create_stroke()
        for i in range(5):
            ia.append_points(s.id, [[i, i, i * 0.1, 1.0]])
            drain([a, b])
        pts = ib.get_stroke(s.id).points
        np.testing.assert_allclose(pts[:, 0], np.arange(5, dtype=np.float32))

    def test_clear_and_summary(self):
        svc, (a,) = make(1, lambda: (Ink("ink"),))
        ink = a.get_channel("ink")
        s = ink.create_stroke()
        ink.append_points(s.id, [[1, 2, 3, 4]])
        drain([a])
        a.submit_summary()
        drain([a])
        b = ContainerRuntime(svc, "doc", channels=(Ink("ink"),))
        assert len(b.get_channel("ink").strokes()) == 1
        ink.clear()
        drain([a])
        assert ink.strokes() == []


class TestSharedSummaryBlock:
    def test_rides_summary_not_ops(self):
        svc, (a, b) = make(2, lambda: (SharedSummaryBlock("sb"),))
        a.get_channel("sb").set("index", {"terms": 40})
        drain([a, b])
        # No op traffic: b does not see it live.
        assert b.get_channel("sb").get("index") is None
        a.submit_summary()
        drain([a, b])
        c = ContainerRuntime(svc, "doc", channels=(SharedSummaryBlock("sb"),))
        assert c.get_channel("sb").get("index") == {"terms": 40}


class TestIdCompressor:
    def mk(self, svc=None):
        svc = svc or LocalFluidService()
        mk1 = lambda s: ContainerRuntime(
            svc, "doc", channels=(IdCompressor("ids", cluster_capacity=8,
                                              session_id=s),)
        )
        return svc, mk1("sess-a"), mk1("sess-b")

    def test_locals_usable_immediately_then_finalize(self):
        svc, a, b = self.mk()
        ca = a.get_channel("ids")
        ids = ca.generate_ids(3)
        assert ids == [-1, -2, -3]
        assert ca.normalize_to_final(-1) is None  # not yet finalized
        ca.take_id_range()
        drain([a, b])
        assert [ca.normalize_to_final(i) for i in ids] == [0, 1, 2]

    def test_cross_session_disjoint_and_convergent(self):
        svc, a, b = self.mk()
        ca, cb = a.get_channel("ids"), b.get_channel("ids")
        ca.generate_ids(3)
        cb.generate_ids(2)
        ca.take_id_range()
        cb.take_id_range()
        drain([a, b])
        fa = [ca.normalize_to_final(-i) for i in (1, 2, 3)]
        fb = [cb.normalize_to_final(-i) for i in (1, 2)]
        assert set(fa).isdisjoint(fb)
        # Both replicas agree on every mapping.
        for f in fa:
            assert ca.decompress(f) == cb.decompress(f) == ("sess-a", fa.index(f))
        for f in fb:
            assert ca.decompress(f)[0] == "sess-b"
        assert ca.recompress("sess-b", 0) == fb[0]

    def test_cluster_reuse_keeps_ids_dense(self):
        svc, a, b = self.mk()
        ca = a.get_channel("ids")
        ca.generate_ids(3)
        ca.take_id_range()
        drain([a, b])
        ca.generate_ids(3)
        ca.take_id_range()
        drain([a, b])
        # Second range fills the same 8-capacity cluster: finals 3..5.
        assert [ca.normalize_to_final(-i) for i in (4, 5, 6)] == [3, 4, 5]
        assert ca._next_final == 8  # still one cluster reserved

    def test_overflow_allocates_new_cluster(self):
        svc, a, b = self.mk()
        ca, cb = a.get_channel("ids"), b.get_channel("ids")
        ca.generate_ids(8)
        ca.take_id_range()
        cb.generate_ids(1)
        cb.take_id_range()
        drain([a, b])
        ca.generate_ids(2)  # overflows sess-a's first cluster
        ca.take_id_range()
        drain([a, b])
        finals = [ca.normalize_to_final(-i) for i in (9, 10)]
        assert finals[0] >= 16  # lands past sess-b's cluster
        assert ca.decompress(finals[1]) == ("sess-a", 9)
        assert cb.decompress(finals[1]) == ("sess-a", 9)

    def test_summary_roundtrip(self):
        svc, a, b = self.mk()
        ca = a.get_channel("ids")
        ca.generate_ids(3)
        ca.take_id_range()
        drain([a, b])
        a.submit_summary()
        drain([a, b])
        c = ContainerRuntime(
            svc, "doc",
            channels=(IdCompressor("ids", cluster_capacity=8, session_id="sess-c"),),
        )
        cc = c.get_channel("ids")
        assert cc.decompress(2) == ("sess-a", 2)
        cc.generate_ids(1)
        cc.take_id_range()
        drain([c, a, b])
        assert cc.normalize_to_final(-1) == 8
        assert ca.decompress(8) == ("sess-c", 0)


class TestSharedMapClearShadowing:
    def test_remote_set_during_pending_local_clear(self):
        """Mirror of the SharedDirectory case on SharedMap (mapKernel
        pendingClearMessageId): a remote set arriving while our clear is
        in flight must not apply — the clear sequences later and wins."""
        from fluidframework_tpu.models.shared_map import SharedMap

        svc = LocalFluidService()
        a = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
        b = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
        ma, mb = a.get_channel("m"), b.get_channel("m")
        ma.set("x", 1)
        drain([a, b])
        ma.set("y", 2)
        mb.clear()
        drain([a, b])  # a flushes first: set @ N, clear @ N+1 wins
        assert not ma.has("y") and not mb.has("y")
        assert not ma.has("x") and not mb.has("x")
