"""Reconnect/resubmit tests: offline edits rebase onto the current state
(reference regeneratePendingOp + reSubmitCore semantics, SURVEY §5.3)."""

import numpy as np
import pytest

from fluidframework_tpu.models.shared_map import SharedMap
from fluidframework_tpu.models.shared_string import SharedString
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.local_server import LocalFluidService

ALPHABET = "abcdefghijklmnopqrstuvwxyz"


def setup(n=2, channel=lambda: SharedString("text")):
    svc = LocalFluidService()
    rts = [ContainerRuntime(svc, "doc", channels=(channel(),)) for _ in range(n)]
    return svc, rts


def drain(rts):
    busy = True
    while busy:
        busy = any(rt.process_incoming() for rt in rts if rt.connected)


def test_offline_insert_rebases():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "hello world")
    drain([a, b])

    a.disconnect()
    sa.insert_text(5, "!")  # offline edit at "hello|!| world"
    sb.insert_text(0, ">> ")  # concurrent edit while a is away
    drain([b])
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text() == ">> hello! world"


def test_offline_remove_rebases():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "abcdef")
    drain([a, b])

    a.disconnect()
    sa.remove_range(2, 4)  # remove "cd" offline
    sb.insert_text(0, "XY")  # shift positions while a is away
    drain([b])
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text() == "XYabef"


def test_offline_remove_superseded_by_remote():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "abcdef")
    drain([a, b])

    a.disconnect()
    sa.remove_range(1, 5)  # offline remove "bcde"
    sb.remove_range(2, 4)  # remote removes "cd" first
    drain([b])
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text() == "af"


def test_offline_insert_then_remove():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "base")
    drain([a, b])

    a.disconnect()
    sa.insert_text(4, "-tail")
    sa.remove_range(0, 2)  # "base-tail" -> "se-tail"
    sa.remove_range(2, 4)  # "se-tail" -> "seail" (spans acked + offline text)
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text() == "seail"


def test_offline_annotate_rebases():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "abcdef")
    drain([a, b])

    a.disconnect()
    sa.annotate(1, 4, 9)
    sb.insert_text(0, "ZZ")
    drain([b])
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text()
    assert sa.annotations() == sb.annotations() == [(3, 6, 9)]


def test_map_offline_resubmit():
    svc, (a, b) = setup(channel=lambda: SharedMap("m"))
    ma, mb = a.get_channel("m"), b.get_channel("m")
    ma.set("x", 1)
    drain([a, b])
    a.disconnect()
    ma.set("x", 2)
    mb.set("y", 3)
    drain([b])
    a.reconnect()
    drain([a, b])
    assert ma.get("x") == mb.get("x") == 2
    assert ma.get("y") == mb.get("y") == 3


@pytest.mark.parametrize("seed", range(4))
def test_reconnect_farm(seed):
    rng = np.random.default_rng(seed + 900)
    svc, rts = setup(3)
    strings = [rt.get_channel("text") for rt in rts]
    strings[0].insert_text(0, "seed")
    drain(rts)

    for step in range(80):
        i = int(rng.integers(0, 3))
        rt, s = rts[i], strings[i]
        act = rng.integers(0, 6)
        length = len(s)
        if act == 0:
            s.insert_text(
                int(rng.integers(0, length + 1)),
                "".join(rng.choice(list(ALPHABET), int(rng.integers(1, 4)))),
            )
        elif act == 1 and length > 2:
            x = int(rng.integers(0, length - 1))
            s.remove_range(x, x + int(rng.integers(1, min(4, length - x) + 1)))
        elif act == 2 and rt.connected:
            rt.flush()
        elif act == 3 and rt.connected:
            rt.process_incoming(int(rng.integers(1, 5)))
        elif act == 4 and rt.connected and sum(r.connected for r in rts) > 1:
            rt.disconnect()
        elif act == 5 and not rt.connected:
            rt.reconnect()

    for rt in rts:
        if not rt.connected:
            rt.reconnect()
    drain(rts)
    texts = [s.get_text() for s in strings]
    assert all(t == texts[0] for t in texts), f"diverged: {texts}"
    assert all(s.err_flags == 0 for s in strings)


def test_offline_remove_split_by_concurrent_insert():
    """A pending remove whose rows get split by a concurrent remote insert
    regenerates as MULTIPLE wire removes; later runs' positions must not
    count earlier runs' rows (hidden by the time they apply remotely)."""
    svc, (a, b) = setup(2)
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "abcdef")
    drain([a, b])

    a.disconnect()
    sa.remove_range(1, 5)  # offline: removes "bcde"
    sb.insert_text(3, "XY")  # lands inside the locally-removed range
    b.flush()
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text() == "aXYf"


def test_recycled_slot_does_not_leak_pending_rows():
    """Pending rows restamp to the new client slot on reconnect: a new
    client recycling the old slot must not see this replica's unacked rows
    through the kernel's own-insert fast path."""
    from fluidframework_tpu.models.shared_string import SharedString
    from fluidframework_tpu.runtime.container import ContainerRuntime
    from fluidframework_tpu.service.local_server import LocalFluidService

    svc = LocalFluidService()
    a = ContainerRuntime(svc, "doc", channels=(SharedString("text"),))
    b = ContainerRuntime(svc, "doc", channels=(SharedString("text"),))
    a.get_channel("text").insert_text(0, "base")
    drain([a, b])
    old_slot = a.client_id

    a.disconnect()
    a.get_channel("text").insert_text(0, "PP")  # pending rows, old stamp
    # Advance the collab window past a's leave so the slot becomes
    # recyclable, then let a new client take it.
    b.send_noop()
    b.process_incoming()
    b.send_noop()
    b.process_incoming()
    c = ContainerRuntime(svc, "doc", channels=(SharedString("text"),))
    assert c.client_id == old_slot, "test needs the slot to recycle"
    c.get_channel("text").insert_text(4, "QQ")
    c.flush()

    a.reconnect()
    drain([a, b, c])
    texts = {
        rt.get_channel("text").get_text() for rt in (a, b, c)
    }
    assert len(texts) == 1, f"divergence: {texts}"
    # Exact content: C's insert lands in "base" untouched by recycling (a
    # recycled slot must not overwrite the old holder's payloads), and A's
    # resubmitted pending insert rebases to the front.
    assert texts.pop() == "PPbaseQQ"


# ---------------------------------------------------------------------------
# Ungraceful connection loss (socket drop / idle eviction): unlike
# disconnect(), in-flight ops may be sequenced-but-unseen. The runtime must
# neither lose nor duplicate them (reference: PendingStateManager replay +
# deli client expiry; ADVICE r1 finding on container.py:418).


def test_ungraceful_drop_sequenced_echo_not_duplicated():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "base")
    drain([a, b])

    sa.insert_text(4, "!")
    a.flush()  # sequenced server-side; echo sits in the dying inbox
    assert a.pending
    a.drop_connection()  # socket dies before the echo is processed
    sb.insert_text(0, ">")
    drain([b])
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text() == ">base!"


def test_ungraceful_drop_unsequenced_op_resubmits_once():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "base")
    drain([a, b])

    # Server-side eviction severs the connection; the client doesn't know.
    svc.disconnect("doc", a.client_id)
    sa.insert_text(4, "!")
    a.flush()  # ConnectionError -> runtime marks itself disconnected
    assert not a.connected
    assert not a.pending  # never reached the wire: held as offline edits
    sb.insert_text(0, ">")
    drain([b])
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text() == ">base!"


def test_ungraceful_drop_mixed_inflight_and_unsent():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "base")
    drain([a, b])

    sa.insert_text(4, "1")
    a.flush()  # op1 sequenced, unseen
    svc.disconnect("doc", a.client_id)  # eviction
    sa.insert_text(5, "2")
    a.flush()  # op2 rejected -> offline
    assert not a.connected
    sb.insert_text(0, ">")
    drain([b])
    a.reconnect()
    drain([a, b])
    # op1 acked via the prior-echo path (not doubled), op2 resubmitted once.
    assert sa.get_text() == sb.get_text() == ">base12"


def test_idle_eviction_then_reconnect():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "base")
    drain([a, b])

    sa.insert_text(4, "!")
    a.flush()  # in flight
    evicted = svc.expire_idle(0.0)  # everyone idles out
    assert evicted >= 1
    a.drop_connection()
    b.drop_connection()
    a.reconnect()
    b.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text() == "base!"


def test_repeated_ungraceful_drops_stack_generations():
    # Flaky network: the socket dies repeatedly and the server only notices
    # (sequences the LEAVEs) long after the client has moved on. Every
    # in-flight op must ack via its own generation; the late LEAVEs resolve
    # the generations by quorum join-seq identity, not sequence windows.
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "base")
    drain([a, b])

    zombie_ids = []
    for ch in "12":
        sa.insert_text(len(sa.get_text()), ch)
        a.flush()  # sequenced; echo unseen
        zombie_ids.append(a.client_id)

        def dead_socket():
            raise ConnectionError("socket already gone")

        a.connection.disconnect = dead_socket  # server can't be told
        a.drop_connection()
        a.reconnect()
    assert len(a._prior_gens) == 2  # both unresolved: no LEAVEs yet
    for zid in zombie_ids:  # the server finally notices, out of band
        svc.disconnect("doc", zid)
    sb.insert_text(0, ">")
    drain([a, b])
    assert not a._prior_gens
    assert sa.get_text() == sb.get_text() == ">base12"


def test_system_messages_survive_dead_connection():
    # send_noop/propose on a dead connection must not crash the caller:
    # the runtime marks itself disconnected and proposals buffer.
    svc, (a, b) = setup()
    drain([a, b])
    svc.disconnect("doc", a.client_id)  # server-side eviction, a unaware
    a.send_noop()  # must not raise
    assert not a.connected
    a.propose("code", "v2")  # buffers for reconnect
    a.reconnect()
    drain([a, b])
    for rt in (a, b):
        rt.send_noop()  # advance the MSN past the proposal seq
    drain([a, b])
    assert a.approved_proposals.get("code") == "v2"
    assert b.approved_proposals.get("code") == "v2"


def test_inflight_proposal_survives_ungraceful_drop():
    # A PROPOSE submitted onto a connection that dies before sequencing it
    # must re-propose after the old client's LEAVE (same recovery contract
    # as operations).
    svc, (a, b) = setup()
    drain([a, b])

    def dead_socket():
        raise ConnectionError("socket already gone")

    old_id = a.client_id
    # Submit the proposal, then sever the server side BEFORE it sequences:
    # emulate by proposing onto a connection whose op was dropped in flight.
    real_submit = a.connection.submit
    a.connection.submit = lambda msg: None  # swallowed by the dying socket
    a.propose("code", "v2")
    assert a._inflight_proposals
    a.connection.submit = real_submit
    a.connection.disconnect = dead_socket
    a.drop_connection()
    a.reconnect()
    svc.disconnect("doc", old_id)  # server notices late -> LEAVE
    drain([a, b])
    for rt in (a, b):
        rt.send_noop()
    drain([a, b])
    assert a.approved_proposals.get("code") == "v2"
    assert b.approved_proposals.get("code") == "v2"


def test_out_of_order_leaves_preserve_authored_order():
    # The server may notice stacked dead connections newest-first; the
    # earlier generation's unsequenced ops must still resubmit before the
    # later one's (authored order), so its LEAVE resolution defers.
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "base")
    drain([a, b])

    zombie_ids = []
    for ch in "12":

        def dead_socket():
            raise ConnectionError("socket already gone")

        a.connection.submit = lambda msg: None  # dying socket swallows
        sa.insert_text(len(sa.get_text()), ch)
        a.flush()  # never reaches the server
        zombie_ids.append(a.client_id)
        a.connection.disconnect = dead_socket
        a.drop_connection()
        a.reconnect()
    assert len(a._prior_gens) == 2
    svc.disconnect("doc", zombie_ids[1])  # newest zombie noticed first
    svc.disconnect("doc", zombie_ids[0])
    drain([a, b])
    assert not a._prior_gens
    assert sa.get_text() == sb.get_text() == "base12"


def test_in_order_leaves_preserve_authored_order():
    # Same as above but the server notices the zombies oldest-first — both
    # generations must still replay under one resubmit bracket.
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "base")
    drain([a, b])

    zombie_ids = []
    for ch in "12":

        def dead_socket():
            raise ConnectionError("socket already gone")

        a.connection.submit = lambda msg: None
        sa.insert_text(len(sa.get_text()), ch)
        a.flush()
        zombie_ids.append(a.client_id)
        a.connection.disconnect = dead_socket
        a.drop_connection()
        a.reconnect()
    svc.disconnect("doc", zombie_ids[0])  # oldest first this time
    svc.disconnect("doc", zombie_ids[1])
    drain([a, b])
    assert not a._prior_gens
    assert sa.get_text() == sb.get_text() == "base12"


def test_attach_and_ops_recover_through_drop():
    # An ATTACH and the attached channel's first ops all swallowed by a
    # dying socket: recovery must re-announce the attach BEFORE the ops
    # regenerate, or remote replicas drop the ops for an unknown channel.
    svc, (a, b) = setup()
    drain([a, b])
    for rt in (a, b):
        rt.register_channel_type("map", SharedMap)

    def dead_socket():
        raise ConnectionError("socket already gone")

    old_id = a.client_id
    a.connection.submit = lambda msg: None  # everything vanishes in flight
    m = a.attach_channel(SharedMap("m2"), "map")
    m.set("k", "v")
    a.flush()
    a.connection.disconnect = dead_socket
    a.drop_connection()
    a.reconnect()
    svc.disconnect("doc", old_id)  # server notices late
    drain([a, b])
    assert "m2" in b.channels
    assert b.get_channel("m2").get("k") == "v"
    assert a.get_channel("m2").get("k") == "v"
