"""Reconnect/resubmit tests: offline edits rebase onto the current state
(reference regeneratePendingOp + reSubmitCore semantics, SURVEY §5.3)."""

import numpy as np
import pytest

from fluidframework_tpu.models.shared_map import SharedMap
from fluidframework_tpu.models.shared_string import SharedString
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.local_server import LocalFluidService

ALPHABET = "abcdefghijklmnopqrstuvwxyz"


def setup(n=2, channel=lambda: SharedString("text")):
    svc = LocalFluidService()
    rts = [ContainerRuntime(svc, "doc", channels=(channel(),)) for _ in range(n)]
    return svc, rts


def drain(rts):
    busy = True
    while busy:
        busy = any(rt.process_incoming() for rt in rts if rt.connected)


def test_offline_insert_rebases():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "hello world")
    drain([a, b])

    a.disconnect()
    sa.insert_text(5, "!")  # offline edit at "hello|!| world"
    sb.insert_text(0, ">> ")  # concurrent edit while a is away
    drain([b])
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text() == ">> hello! world"


def test_offline_remove_rebases():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "abcdef")
    drain([a, b])

    a.disconnect()
    sa.remove_range(2, 4)  # remove "cd" offline
    sb.insert_text(0, "XY")  # shift positions while a is away
    drain([b])
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text() == "XYabef"


def test_offline_remove_superseded_by_remote():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "abcdef")
    drain([a, b])

    a.disconnect()
    sa.remove_range(1, 5)  # offline remove "bcde"
    sb.remove_range(2, 4)  # remote removes "cd" first
    drain([b])
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text() == "af"


def test_offline_insert_then_remove():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "base")
    drain([a, b])

    a.disconnect()
    sa.insert_text(4, "-tail")
    sa.remove_range(0, 2)  # "base-tail" -> "se-tail"
    sa.remove_range(2, 4)  # "se-tail" -> "seail" (spans acked + offline text)
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text() == "seail"


def test_offline_annotate_rebases():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "abcdef")
    drain([a, b])

    a.disconnect()
    sa.annotate(1, 4, 9)
    sb.insert_text(0, "ZZ")
    drain([b])
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text()
    assert sa.annotations() == sb.annotations() == [(3, 6, 9)]


def test_map_offline_resubmit():
    svc, (a, b) = setup(channel=lambda: SharedMap("m"))
    ma, mb = a.get_channel("m"), b.get_channel("m")
    ma.set("x", 1)
    drain([a, b])
    a.disconnect()
    ma.set("x", 2)
    mb.set("y", 3)
    drain([b])
    a.reconnect()
    drain([a, b])
    assert ma.get("x") == mb.get("x") == 2
    assert ma.get("y") == mb.get("y") == 3


@pytest.mark.parametrize("seed", range(4))
def test_reconnect_farm(seed):
    rng = np.random.default_rng(seed + 900)
    svc, rts = setup(3)
    strings = [rt.get_channel("text") for rt in rts]
    strings[0].insert_text(0, "seed")
    drain(rts)

    for step in range(80):
        i = int(rng.integers(0, 3))
        rt, s = rts[i], strings[i]
        act = rng.integers(0, 6)
        length = len(s)
        if act == 0:
            s.insert_text(
                int(rng.integers(0, length + 1)),
                "".join(rng.choice(list(ALPHABET), int(rng.integers(1, 4)))),
            )
        elif act == 1 and length > 2:
            x = int(rng.integers(0, length - 1))
            s.remove_range(x, x + int(rng.integers(1, min(4, length - x) + 1)))
        elif act == 2 and rt.connected:
            rt.flush()
        elif act == 3 and rt.connected:
            rt.process_incoming(int(rng.integers(1, 5)))
        elif act == 4 and rt.connected and sum(r.connected for r in rts) > 1:
            rt.disconnect()
        elif act == 5 and not rt.connected:
            rt.reconnect()

    for rt in rts:
        if not rt.connected:
            rt.reconnect()
    drain(rts)
    texts = [s.get_text() for s in strings]
    assert all(t == texts[0] for t in texts), f"diverged: {texts}"
    assert all(s.err_flags == 0 for s in strings)


def test_offline_remove_split_by_concurrent_insert():
    """A pending remove whose rows get split by a concurrent remote insert
    regenerates as MULTIPLE wire removes; later runs' positions must not
    count earlier runs' rows (hidden by the time they apply remotely)."""
    svc, (a, b) = setup(2)
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "abcdef")
    drain([a, b])

    a.disconnect()
    sa.remove_range(1, 5)  # offline: removes "bcde"
    sb.insert_text(3, "XY")  # lands inside the locally-removed range
    b.flush()
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text() == "aXYf"


def test_recycled_slot_does_not_leak_pending_rows():
    """Pending rows restamp to the new client slot on reconnect: a new
    client recycling the old slot must not see this replica's unacked rows
    through the kernel's own-insert fast path."""
    from fluidframework_tpu.models.shared_string import SharedString
    from fluidframework_tpu.runtime.container import ContainerRuntime
    from fluidframework_tpu.service.local_server import LocalFluidService

    svc = LocalFluidService()
    a = ContainerRuntime(svc, "doc", channels=(SharedString("text"),))
    b = ContainerRuntime(svc, "doc", channels=(SharedString("text"),))
    a.get_channel("text").insert_text(0, "base")
    drain([a, b])
    old_slot = a.client_id

    a.disconnect()
    a.get_channel("text").insert_text(0, "PP")  # pending rows, old stamp
    # Advance the collab window past a's leave so the slot becomes
    # recyclable, then let a new client take it.
    b.send_noop()
    b.process_incoming()
    b.send_noop()
    b.process_incoming()
    c = ContainerRuntime(svc, "doc", channels=(SharedString("text"),))
    assert c.client_id == old_slot, "test needs the slot to recycle"
    c.get_channel("text").insert_text(4, "QQ")
    c.flush()

    a.reconnect()
    drain([a, b, c])
    texts = {
        rt.get_channel("text").get_text() for rt in (a, b, c)
    }
    assert len(texts) == 1, f"divergence: {texts}"
    # Exact content: C's insert lands in "base" untouched by recycling (a
    # recycled slot must not overwrite the old holder's payloads), and A's
    # resubmitted pending insert rebases to the front.
    assert texts.pop() == "PPbaseQQ"
