"""Reconnect/resubmit tests: offline edits rebase onto the current state
(reference regeneratePendingOp + reSubmitCore semantics, SURVEY §5.3)."""

import numpy as np
import pytest

from fluidframework_tpu.models.shared_map import SharedMap
from fluidframework_tpu.models.shared_string import SharedString
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.local_server import LocalFluidService

ALPHABET = "abcdefghijklmnopqrstuvwxyz"


def setup(n=2, channel=lambda: SharedString("text")):
    svc = LocalFluidService()
    rts = [ContainerRuntime(svc, "doc", channels=(channel(),)) for _ in range(n)]
    return svc, rts


def drain(rts):
    busy = True
    while busy:
        busy = any(rt.process_incoming() for rt in rts if rt.connected)


def test_offline_insert_rebases():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "hello world")
    drain([a, b])

    a.disconnect()
    sa.insert_text(5, "!")  # offline edit at "hello|!| world"
    sb.insert_text(0, ">> ")  # concurrent edit while a is away
    drain([b])
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text() == ">> hello! world"


def test_offline_remove_rebases():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "abcdef")
    drain([a, b])

    a.disconnect()
    sa.remove_range(2, 4)  # remove "cd" offline
    sb.insert_text(0, "XY")  # shift positions while a is away
    drain([b])
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text() == "XYabef"


def test_offline_remove_superseded_by_remote():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "abcdef")
    drain([a, b])

    a.disconnect()
    sa.remove_range(1, 5)  # offline remove "bcde"
    sb.remove_range(2, 4)  # remote removes "cd" first
    drain([b])
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text() == "af"


def test_offline_insert_then_remove():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "base")
    drain([a, b])

    a.disconnect()
    sa.insert_text(4, "-tail")
    sa.remove_range(0, 2)  # "base-tail" -> "se-tail"
    sa.remove_range(2, 4)  # "se-tail" -> "seail" (spans acked + offline text)
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text() == "seail"


def test_offline_annotate_rebases():
    svc, (a, b) = setup()
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "abcdef")
    drain([a, b])

    a.disconnect()
    sa.annotate(1, 4, 9)
    sb.insert_text(0, "ZZ")
    drain([b])
    a.reconnect()
    drain([a, b])
    assert sa.get_text() == sb.get_text()
    assert sa.annotations() == sb.annotations() == [(3, 6, 9)]


def test_map_offline_resubmit():
    svc, (a, b) = setup(channel=lambda: SharedMap("m"))
    ma, mb = a.get_channel("m"), b.get_channel("m")
    ma.set("x", 1)
    drain([a, b])
    a.disconnect()
    ma.set("x", 2)
    mb.set("y", 3)
    drain([b])
    a.reconnect()
    drain([a, b])
    assert ma.get("x") == mb.get("x") == 2
    assert ma.get("y") == mb.get("y") == 3


@pytest.mark.parametrize("seed", range(4))
def test_reconnect_farm(seed):
    rng = np.random.default_rng(seed + 900)
    svc, rts = setup(3)
    strings = [rt.get_channel("text") for rt in rts]
    strings[0].insert_text(0, "seed")
    drain(rts)

    for step in range(80):
        i = int(rng.integers(0, 3))
        rt, s = rts[i], strings[i]
        act = rng.integers(0, 6)
        length = len(s)
        if act == 0:
            s.insert_text(
                int(rng.integers(0, length + 1)),
                "".join(rng.choice(list(ALPHABET), int(rng.integers(1, 4)))),
            )
        elif act == 1 and length > 2:
            x = int(rng.integers(0, length - 1))
            s.remove_range(x, x + int(rng.integers(1, min(4, length - x) + 1)))
        elif act == 2 and rt.connected:
            rt.flush()
        elif act == 3 and rt.connected:
            rt.process_incoming(int(rng.integers(1, 5)))
        elif act == 4 and rt.connected and sum(r.connected for r in rts) > 1:
            rt.disconnect()
        elif act == 5 and not rt.connected:
            rt.reconnect()

    for rt in rts:
        if not rt.connected:
            rt.reconnect()
    drain(rts)
    texts = [s.get_text() for s in strings]
    assert all(t == texts[0] for t in texts), f"diverged: {texts}"
    assert all(s.err_flags == 0 for s in strings)
