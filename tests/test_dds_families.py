"""The remaining DDS families: legacy tree, OT json, PropertyDDS, SparseMatrix
(reference experimental/dds/* + PropertyDDS + sequence-deprecated)."""

import pytest

from fluidframework_tpu.models.ot_json import SharedOTJson, apply_op, transform
from fluidframework_tpu.models.property_dds import (
    SharedPropertyTree,
    apply_changeset,
    empty_changeset,
    rebase,
    squash,
)
from fluidframework_tpu.models.sparse_matrix import SparseMatrix
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.local_server import LocalFluidService
from fluidframework_tpu.tree.legacy_tree import LegacySharedTree


def setup(channel_factory, n=2, doc="fam-doc"):
    svc = LocalFluidService()
    rts = [
        ContainerRuntime(svc, doc, channels=(channel_factory(),))
        for _ in range(n)
    ]
    return svc, rts


def drain(rts):
    for rt in rts:
        rt.flush()
    busy = True
    while busy:
        busy = any(rt.process_incoming() for rt in rts)


# ---------------------------------------------------------------------------
# Legacy SharedTree


def test_legacy_tree_edits_history_and_undo():
    svc, (a, b) = setup(lambda: LegacySharedTree("t"))
    ta, tb = a.get_channel("t"), b.get_channel("t")
    nid = ta.insert_node(0, "kids", {"type": "n", "value": "hello"})
    drain([a, b])
    assert tb.current_view() == ta.current_view()
    assert len(tb.edit_log) == 1

    e2 = ta.apply_edit({"k": "val", "id": nid, "value": "changed"})
    drain([a, b])
    assert tb.current_view()["fields"]["kids"][0]["value"] == "changed"

    # History: revision views before/after; undo restores the old value.
    view_before = tb.log_viewer.revision_at(1)
    assert view_before.subtree(0)["fields"]["kids"][0]["value"] == "hello"
    ta.undo(e2)
    drain([a, b])
    assert tb.current_view()["fields"]["kids"][0]["value"] == "hello"


def test_legacy_tree_constraint_drops_whole_edit():
    svc, (a, b) = setup(lambda: LegacySharedTree("t"))
    ta, tb = a.get_channel("t"), b.get_channel("t")
    nid = ta.insert_node(0, "kids", {"type": "n", "value": 1})
    drain([a, b])
    # a's edit requires kids to still have exactly 1 element; b concurrently
    # inserts, sequencing first -> a's whole edit becomes a no-op everywhere.
    tb.insert_node(0, "kids", {"type": "n", "value": 2})
    b.flush()
    ta.apply_edit(
        {"k": "constraint", "parent": 0, "field": "kids", "length": 1},
        {"k": "val", "id": nid, "value": 99},
    )
    drain([a, b])
    assert ta.current_view() == tb.current_view()
    vals = [k["value"] for k in ta.current_view()["fields"]["kids"]]
    assert 99 not in vals, "constrained edit must drop atomically"


# ---------------------------------------------------------------------------
# OT json


def test_ot_transform_list_indices():
    op = {"p": [5], "li": "x"}
    assert transform(op, {"p": [2], "li": "y"})["p"] == [6]
    assert transform(op, {"p": [2], "ld": 1})["p"] == [4]
    assert transform({"p": [2], "ld": 1}, {"p": [2], "ld": 1}) is None
    # Delete of an ancestor kills nested edits.
    assert transform({"p": ["a", "b"], "oi": 1}, {"p": ["a"], "od": 1}) is None


def test_ot_json_concurrent_lists_converge():
    svc, (a, b) = setup(lambda: SharedOTJson("j", initial={"items": []}))
    ja, jb = a.get_channel("j"), b.get_channel("j")
    ja.list_insert(["items"], 0, "from-a")
    jb.list_insert(["items"], 0, "from-b")
    drain([a, b])
    assert ja.as_data() == jb.as_data()
    assert set(ja.get("items")) == {"from-a", "from-b"}

    ja.list_delete(["items"], 0)
    jb.list_insert(["items"], 2, "tail")
    drain([a, b])
    assert ja.as_data() == jb.as_data()
    assert len(ja.get("items")) == 2


def test_ot_json_number_add_commutes():
    svc, (a, b) = setup(lambda: SharedOTJson("j", initial={"n": 0}))
    ja, jb = a.get_channel("j"), b.get_channel("j")
    ja.number_add(["n"], 5)
    jb.number_add(["n"], 7)
    drain([a, b])
    assert ja.get("n") == jb.get("n") == 12


def test_ot_json_delete_vs_nested_edit():
    svc, (a, b) = setup(
        lambda: SharedOTJson("j", initial={"cfg": {"x": 1}})
    )
    ja, jb = a.get_channel("j"), b.get_channel("j")
    ja.delete_key(["cfg"])
    jb.set_key(["cfg", "x"], 99)  # concurrent edit inside deleted subtree
    drain([a, b])
    assert ja.as_data() == jb.as_data()
    assert ja.get("cfg") is None


# ---------------------------------------------------------------------------
# PropertyDDS


def test_property_changeset_algebra():
    a = {"insert": {"p.x": ("Int32", 1)}, "modify": {}, "remove": []}
    b = {"insert": {}, "modify": {"p.x": 2}, "remove": []}
    sq = squash(a, b)
    doc = {}
    apply_changeset(doc, sq)
    assert doc["p.x"] == ("Int32", 2)
    # squash associativity on a sample.
    c = {"insert": {}, "modify": {}, "remove": ["p.x"]}
    d1, d2 = {}, {}
    apply_changeset(d1, squash(squash(a, b), c))
    apply_changeset(d2, squash(a, squash(b, c)))
    assert d1 == d2
    # rebase drops edits under a removed subtree.
    r = rebase(b, c)
    assert not r["modify"]


def test_property_tree_commit_and_convergence():
    svc, (a, b) = setup(lambda: SharedPropertyTree("p"))
    pa, pb = a.get_channel("p"), b.get_channel("p")
    pa.insert_property("car.speed", "Int32", 60)
    pa.insert_property("car.name", "String", "zippy")
    pa.commit()
    drain([a, b])
    assert pb.get("car.speed") == 60
    with pytest.raises(TypeError):
        pb.set_value("car.speed", "fast")  # typed set enforces Int32

    # Concurrent: a modifies; b removes the subtree. Removal sequences
    # first; a's rebase drops the modify.
    pb.remove_property("car.speed")
    pb.commit()
    b.flush()
    pa.set_value("car.speed", 80)
    pa.commit()
    drain([a, b])
    assert pa.get("car.speed") == pb.get("car.speed") is None
    assert pa.get("car.name") == "zippy"


def test_property_tree_summary_roundtrip():
    svc, (a,) = setup(lambda: SharedPropertyTree("p"), n=1)
    pa = a.get_channel("p")
    pa.insert_property("cfg.flag", "Bool", True)
    pa.commit()
    drain([a])
    a.submit_summary()
    drain([a])
    late = ContainerRuntime(
        svc, "fam-doc", channels=(SharedPropertyTree("p"),)
    )
    drain([a, late])
    assert late.get_channel("p").get("cfg.flag") is True


# ---------------------------------------------------------------------------
# SparseMatrix


def test_sparse_matrix_rows_and_cells_converge():
    svc, (a, b) = setup(lambda: SparseMatrix("sm"))
    ma, mb = a.get_channel("sm"), b.get_channel("sm")
    ma.insert_rows(0, 3)
    drain([a, b])
    ma.set_cell(0, 0, "r0c0")
    ma.set_cell(2, 8000, "r2-far")  # huge virtual column space
    drain([a, b])
    assert mb.get_cell(0, 0) == "r0c0"
    assert mb.get_cell(2, 8000) == "r2-far"

    # Concurrent row inserts at the same position converge.
    ma.insert_rows(1, 1)
    mb.insert_rows(1, 1)
    drain([a, b])
    assert ma.row_count == mb.row_count == 5
    # Cells ride their row handles through reordering.
    assert mb.get_cell(0, 0) == "r0c0"
    assert [ma.row_values(r) for r in range(5)] == [
        mb.row_values(r) for r in range(5)
    ]


def test_sparse_matrix_remove_rows_and_summary():
    svc, (a,) = setup(lambda: SparseMatrix("sm"), n=1)
    ma = a.get_channel("sm")
    ma.insert_rows(0, 4)
    drain([a])
    for r in range(4):
        ma.set_cell(r, 1, f"row{r}")
    drain([a])
    ma.remove_rows(1, 2)
    drain([a])
    assert ma.row_count == 2
    assert ma.get_cell(0, 1) == "row0"
    assert ma.get_cell(1, 1) == "row3"

    a.submit_summary()
    drain([a])
    late = ContainerRuntime(svc, "fam-doc", channels=(SparseMatrix("sm"),))
    drain([a, late])
    ml = late.get_channel("sm")
    assert ml.row_count == 2
    assert ml.get_cell(1, 1) == "row3"


def test_ot_bridges_over_already_acked_ops():
    """Remote ops whose author had not seen our ALREADY-SEQUENCED ops must
    transform over them (total-order bridging), not apply raw."""
    svc, (a, b) = setup(
        lambda: SharedOTJson("j", initial={"items": ["a", "b", "c", "d", "e"]})
    )
    ja, jb = a.get_channel("j"), b.get_channel("j")
    ja.list_insert(["items"], 0, "X")
    jb.list_insert(["items"], 5, "Y")
    # a's op sequences (and acks at a) before b's arrives at a.
    a.flush()
    a.process_incoming()
    drain([a, b])
    assert ja.as_data() == jb.as_data()
    assert ja.get("items") == ["X", "a", "b", "c", "d", "e", "Y"]


def test_ot_progressive_transform_across_batches():
    """Later pending batches transform against the PROGRESSIVELY transformed
    remote (an annihilated remote op must not shift them)."""
    svc, (a, b) = setup(
        lambda: SharedOTJson("j", initial={"items": ["a", "b"]})
    )
    ja, jb = a.get_channel("j"), b.get_channel("j")
    jb.list_delete(["items"], 0)
    b.flush()
    ja.list_delete(["items"], 0)  # same element: annihilates vs remote
    ja.list_insert(["items"], 1, "x")  # second batch
    drain([a, b])
    assert ja.as_data() == jb.as_data()
    assert ja.get("items") == ["b", "x"]


@pytest.mark.parametrize("seed", range(4))
def test_ot_json_fuzz_convergence(seed):
    import numpy as np

    rng = np.random.default_rng(seed)
    svc, rts = setup(
        lambda: SharedOTJson("j", initial={"items": list("abcd"), "n": 0}),
        n=3,
    )
    docs = [rt.get_channel("j") for rt in rts]
    for step in range(100):
        i = int(rng.integers(0, 3))
        d = docs[i]
        items = d.get("items")
        roll = rng.random()
        if roll < 0.45:
            d.list_insert(["items"], int(rng.integers(0, len(items) + 1)),
                          f"s{step}")
        elif roll < 0.7 and items:
            d.list_delete(["items"], int(rng.integers(0, len(items))))
        elif roll < 0.85:
            d.number_add(["n"], int(rng.integers(1, 5)))
        else:
            d.set_key([f"k{int(rng.integers(0, 4))}"], step)
        if step % 3 == 0:
            rts[i].flush()
        if step % 5 == 0:
            for rt in rts:
                rt.process_incoming()
    drain(rts)
    datas = [d.as_data() for d in docs]
    assert datas[0] == datas[1] == datas[2]


def test_property_remove_preexisting_with_staged_child_insert():
    """remove_property on a pre-existing path must survive squash even when
    the same staged changeset inserted a child under it."""
    svc, (a, b) = setup(lambda: SharedPropertyTree("p"))
    pa, pb = a.get_channel("p"), b.get_channel("p")
    pa.insert_property("a", "Int32", 1)
    pa.commit()
    drain([a, b])
    pa.insert_property("a.b", "Int32", 2)
    pa.remove_property("a")
    pa.commit()
    drain([a, b])
    assert pb.get("a") is None and pa.get("a") is None
    assert pb.get("a.b") is None


def test_legacy_tree_edit_references_its_own_insert():
    """Changes inside one edit see their predecessors (insert then set)."""
    svc, (a, b) = setup(lambda: LegacySharedTree("t"))
    ta, tb = a.get_channel("t"), b.get_channel("t")
    node = ta._assign_ids({"type": "n"})
    ta.apply_edit(
        {"k": "ins", "parent": 0, "field": "kids", "anchor": None,
         "nodes": [node]},
        {"k": "val", "id": node["id"], "value": "set-in-same-edit"},
    )
    drain([a, b])
    assert (
        tb.current_view()["fields"]["kids"][0]["value"]
        == "set-in-same-edit"
    )


def test_view_adapter_detaches():
    from fluidframework_tpu.framework.helpers import ViewAdapter
    from fluidframework_tpu.models.shared_string import SharedString

    svc, (a, b) = setup(lambda: SharedString("text"))
    views = []
    adapter = ViewAdapter(b, "text", lambda s: s.get_text())
    adapter.subscribe(views.append)
    a.get_channel("text").insert_text(0, "x")
    drain([a, b])
    n = len(views)
    adapter.detach()
    a.get_channel("text").insert_text(0, "y")
    drain([a, b])
    assert len(views) == n, "detached adapter must stop rendering"


def test_legacy_tree_undo_of_dependent_changes():
    """Undo of an edit whose later changes reference its earlier inserts
    (inverses derive against intermediate states)."""
    svc, (a, b) = setup(lambda: LegacySharedTree("t"))
    ta, tb = a.get_channel("t"), b.get_channel("t")
    node = ta._assign_ids({"type": "n"})
    eid = ta.apply_edit(
        {"k": "ins", "parent": 0, "field": "kids", "anchor": None,
         "nodes": [node]},
        {"k": "val", "id": node["id"], "value": 7},
    )
    drain([a, b])
    ta.undo(eid)
    drain([a, b])
    assert ta.current_view() == tb.current_view()
    assert not ta.current_view().get("fields", {}).get("kids")

    # Undo of a dropped edit is a no-op (returns None, nothing sent).
    eid2 = ta.apply_edit({"k": "del", "id": 999999})
    drain([a, b])
    assert ta.undo(eid2) is None


def test_property_array_ot_converges():
    """ArrayProperty positional OT: concurrent inserts/removes converge
    with later-writer-first tie order and remove annihilation."""
    svc, (a, b) = setup(lambda: SharedPropertyTree("p"))
    pa, pb = a.get_channel("p"), b.get_channel("p")
    pa.insert_array_property("tags", ["x", "y", "z"])
    pa.commit()
    drain([a, b])
    assert pb.get("tags") == ["x", "y", "z"]

    # Concurrent: a inserts at front, b removes the middle.
    pa.array_insert("tags", 0, ["a0"])
    pa.commit()
    a.flush()
    pb.array_remove("tags", 1)  # removes "y" in b's view
    pb.commit()
    drain([a, b])
    assert pa.get("tags") == pb.get("tags") == ["a0", "x", "z"]

    # Concurrent removes of the same element annihilate (no double kill).
    pa.array_remove("tags", 1)
    pa.commit()
    a.flush()
    pb.array_remove("tags", 1)
    pb.commit()
    drain([a, b])
    assert pa.get("tags") == pb.get("tags") == ["a0", "z"]


@pytest.mark.parametrize("seed", range(3))
def test_property_array_fuzz(seed):
    import numpy as np

    rng = np.random.default_rng(seed)
    svc, rts = setup(lambda: SharedPropertyTree("p"), n=3)
    docs = [rt.get_channel("p") for rt in rts]
    docs[0].insert_array_property("arr", [0, 1, 2, 3])
    docs[0].commit()
    drain(rts)
    for step in range(80):
        i = int(rng.integers(0, 3))
        d = docs[i]
        arr = d.get("arr") or []
        if arr and rng.random() < 0.4:
            d.array_remove("arr", int(rng.integers(0, len(arr))))
        else:
            d.array_insert("arr", int(rng.integers(0, len(arr) + 1)),
                           [100 + step])
        d.commit()
        if step % 3 == 0:
            rts[i].flush()
        if step % 5 == 0:
            for rt in rts:
                rt.process_incoming()
    drain(rts)
    vals = [d.get("arr") for d in docs]
    assert vals[0] == vals[1] == vals[2]


def test_squash_remove_reinsert_keeps_array_ops():
    # ADVICE r1: a single changeset with remove[p] + insert[p] + arrays[p]
    # (remove, reinsert, then edit the new array) must keep its own array
    # ops under the compose law apply(doc, squash(a,b)) == apply(apply(doc,a), b).
    a = {"insert": {"p": ("Array", [1, 2, 3])}, "modify": {}, "remove": [],
         "arrays": {}}
    b = {"insert": {"p": ("Array", [])}, "modify": {}, "remove": ["p"],
         "arrays": {"p": [{"i": 0, "ins": [9]}]}}
    d1, d2 = {}, {}
    apply_changeset(d1, squash(a, b))
    apply_changeset(d2, a)
    apply_changeset(d2, b)
    assert d1 == d2 == {"p": ("Array", [9])}
