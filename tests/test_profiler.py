"""Serving timeline profiler (telemetry/profiler.py): lane vocabulary,
bounded arm/disarm over a real websocket server, the zero-cost-disarmed
/ zero-readback contracts, Perfetto export, the derived-view equivalence
of the legacy counters, the /profilez shed-tier contract (NOT exempt),
and the two runtime watchdogs (loop-stall sentinel, gc pause hooks).

The r16 acceptance bar: a captured window decomposes the serving wall
into named lanes plus the derived per-boxcar ``loop_other`` host tax,
``pump_busy_s``/``flush_totals["staging_s"]`` are exact derived views of
the same interval clock reads, and /profilez sheds under overload while
/metrics and /debugz stay exempt.
"""

import gc
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from fluidframework_tpu.protocol.constants import (
    F_ARG,
    F_LEN,
    F_REF,
    F_SEQ,
    F_TYPE,
    OP_INSERT,
    OP_WIDTH,
)
from fluidframework_tpu.protocol.opframe import OpFrame, SeqFrame
from fluidframework_tpu.service.device_backend import DeviceFleetBackend
from fluidframework_tpu.service.pipeline import PipelineFluidService
from fluidframework_tpu.telemetry import journal, metrics, profiler, tracing
from fluidframework_tpu.testing import faults

MINT = 1 << 14  # shared_string._MINT_STRIDE


@pytest.fixture(autouse=True)
def _clean_profiler():
    profiler.reset()
    journal.enable()
    journal.reset()
    faults.reset()
    metrics.REGISTRY.reset()
    yield
    faults.reset()
    profiler.reset()
    journal.enable()
    journal.reset()
    metrics.REGISTRY.reset()


def _feed(be, r: int, n_ch: int = 6, k: int = 8) -> None:
    ar = np.arange(k, dtype=np.int32)
    for i in range(n_ch):
        rows = np.zeros((k, OP_WIDTH), np.int32)
        rows[:, F_TYPE] = OP_INSERT
        rows[:, F_LEN] = 1
        rows[:, F_SEQ] = r * k + 1 + ar
        rows[:, F_REF] = r * k
        rows[:, F_ARG] = r * k + 1 + ar
        be.enqueue_frame(f"d{i}", SeqFrame("s", 0, 1, rows, (), 0.0))


def _pump_rounds(be, rounds: int = 4) -> None:
    for r in range(rounds):
        _feed(be, r)
        be.pump_stage()
        be.pump_dispatch()
    be.pump_drain()


def _one_frame(conn, svc, doc, k=3, c0=1):
    origs = [conn.conn_no * MINT + c0 + j for j in range(k)]
    return OpFrame.build(
        "s", ["ins"] * k, [0] * k, origs, ["x"] * k, csn0=c0,
        ref=svc.doc_head(doc),
    )


# ---------------------------------------------------------------------------
# Lane vocabulary


def test_lane_vocabulary_covers_the_trace_spine():
    """Every pump/feed sub-stage the r10/r12 trace spine names has a
    timeline lane (ring_stage's upload half is the ring_put lane), the
    deli ticket has its own lane, the derived gap and both watchdog
    lanes are declared, and the Perfetto tids are the deterministic
    declaration order."""
    spine_to_lane = {
        tracing.STAGE_DEVICE_STEP: "device_step",
        tracing.STAGE_SCAN_CONSUME: "scan_consume",
        tracing.STAGE_FEED_WAIT: "feed_wait",
        tracing.STAGE_RING_STAGE: "ring_put",
        tracing.STAGE_DELI: "ticket",
    }
    for stage, lane in spine_to_lane.items():
        assert stage in tracing.FRAME_STAGES
        assert lane in profiler.LANES, (stage, lane)
    for lane in ("host_stage", "dispatch", "loop_other", "loop_lag",
                 "gc_pause"):
        assert lane in profiler.LANES
    assert profiler.ROUND_LANES <= set(profiler.LANES)
    assert sorted(profiler.LANE_TIDS.values()) == list(
        range(len(profiler.LANES))
    )


def test_unknown_lane_raises():
    assert profiler.arm(5000)
    with pytest.raises(ValueError):
        profiler.PROFILER.record("not.a.lane", 0.0, 1.0)


def test_loop_other_is_derived_not_recordable():
    """loop_other is the SYNTHESIZED gap: recording it directly would
    double-count the host tax."""
    assert profiler.arm(5000)
    with pytest.raises(ValueError):
        profiler.PROFILER.record("loop_other", 0.0, 1.0)


def test_ring_is_bounded():
    p = profiler.Profiler(capacity=64)
    p._until = float("inf")
    for i in range(100):
        p.record("host_stage", float(i), float(i) + 0.5, boxcar=i)
    ivs = p.intervals()
    assert len(ivs) == 64
    assert [iv.iid for iv in ivs] == list(range(36, 100))
    assert p.seen == 100


# ---------------------------------------------------------------------------
# Deterministic test surface vs wall-timestamped export


def test_render_is_replica_deterministic():
    """Two profilers observing the same LOGICAL intervals at different
    wall times render byte-equal text — timestamps live only in the
    exported trace file."""
    a, b = profiler.Profiler(), profiler.Profiler()
    a._until = b._until = float("inf")
    for p, skew in ((a, 0.0), (b, 17.3)):
        t = 100.0 + skew
        p.record("host_stage", t, t + 0.001, boxcar=1, rows=48)
        p.record("ring_put", t + 0.001, t + 0.002, boxcar=1, rows=48)
        p.record("device_step", t + 0.002, t + 0.009, boxcar=1)
        p.record("gc_pause", t + 0.5, t + 0.51)
    assert a.render() == b.render()
    assert a.render().splitlines()[1] == "000000 host_stage boxcar=1 rows=48"
    # The export DOES carry the wall microseconds.
    ts_a = [
        e["ts"] for e in a.chrome_trace()["traceEvents"] if e["ph"] == "X"
    ]
    ts_b = [
        e["ts"] for e in b.chrome_trace()["traceEvents"] if e["ph"] == "X"
    ]
    assert ts_a != ts_b


def test_chrome_trace_schema_and_loop_other_synthesis():
    """The Perfetto export: valid JSON, pid=process / one metadata-named
    tid per lane, complete events with µs ts+dur, and the derived
    loop_other gaps synthesized per boxcar round."""
    import os

    p = profiler.Profiler()
    p._until = float("inf")
    # One round with a gap between ring_put and dispatch (the host tax).
    p.record("host_stage", 10.000, 10.001, boxcar=7, rows=8)
    p.record("ring_put", 10.001, 10.002, boxcar=7, rows=8)
    p.record("dispatch", 10.004, 10.005, boxcar=7)
    p.record("device_step", 10.005, 10.010, boxcar=7)
    doc = json.loads(json.dumps(p.chrome_trace()))
    evs = doc["traceEvents"]
    meta = {e["args"]["name"] for e in evs if e["ph"] == "M"
            if e["name"] == "thread_name"}
    assert meta == set(profiler.LANES)
    xs = [e for e in evs if e["ph"] == "X"]
    for e in xs:
        assert e["pid"] == os.getpid()
        assert e["tid"] == profiler.LANE_TIDS[e["name"]]
        assert e["ts"] >= 0 and e["dur"] >= 0
        assert set(e["args"]) == {"boxcar", "rows"}
    gaps = [e for e in xs if e["name"] == "loop_other"]
    assert len(gaps) == 1
    assert gaps[0]["args"]["boxcar"] == 7
    # The synthesized gap is ring_put end -> dispatch start (2ms).
    assert abs(gaps[0]["dur"] - 2000.0) < 1.0


# ---------------------------------------------------------------------------
# Cost contracts


def test_zero_alloc_when_disarmed(monkeypatch):
    """Disarmed (the default), the profiler allocates NOTHING: every
    producer site is one predicate; the counting shim pins that no
    record call reaches the ring through a full pipeline workload."""
    calls = []
    orig = profiler.Profiler.record

    def counting(self, lane, t0, t1, boxcar=-1, rows=0):
        calls.append(lane)
        return orig(self, lane, t0, t1, boxcar=boxcar, rows=rows)

    monkeypatch.setattr(profiler.Profiler, "record", counting)
    assert not profiler.enabled()
    svc = PipelineFluidService(n_partitions=2)
    conn = svc.connect("off-doc")
    conn.submit_frame(_one_frame(conn, svc, "off-doc"))
    svc.pump()
    svc.flush_device()
    assert calls == []
    assert profiler.PROFILER.seen == 0
    assert profiler.arm(5000)
    conn.submit_frame(_one_frame(conn, svc, "off-doc", c0=4))
    svc.pump()
    svc.flush_device()
    assert "ticket" in calls and "host_stage" in calls, calls


def test_profiler_adds_zero_device_readbacks(monkeypatch):
    """The zero-readback contract: an armed capture performs EXACTLY the
    same device→host transfers as a disarmed run — device_step closes on
    the pump's existing one-boxcar-stale scan, never its own pull."""
    from fluidframework_tpu.parallel import fleet as fleet_mod
    from fluidframework_tpu.service import device_backend as db_mod

    def run() -> int:
        be = DeviceFleetBackend(
            capacity=128, max_batch=1 << 20, pump_mode=True
        )
        calls = []
        real = np.asarray

        class _CountingNp:
            def __getattr__(self, name):
                return getattr(np, name)

            @staticmethod
            def asarray(*a, **kw):
                calls.append(1)
                return real(*a, **kw)

            @staticmethod
            def array(*a, **kw):
                calls.append(1)
                return np.array(*a, **kw)

        monkeypatch.setattr(fleet_mod, "np", _CountingNp())
        monkeypatch.setattr(db_mod, "np", _CountingNp())
        try:
            for r in range(3):
                _feed(be, r, n_ch=4, k=4)
                be.flush()
            be.pump_drain()
        finally:
            monkeypatch.setattr(fleet_mod, "np", np)
            monkeypatch.setattr(db_mod, "np", np)
        return len(calls)

    profiler.disarm()
    off = run()
    assert profiler.arm(30_000)
    on = run()
    assert on == off, f"profiler added readbacks: on={on} off={off}"
    assert profiler.PROFILER.seen > 0


# ---------------------------------------------------------------------------
# The derived-view satellite: one clock, one record site


def test_legacy_counters_are_derived_views_pump():
    """``pump_busy_s`` and ``flush_totals['staging_s']`` accumulate from
    the SAME perf_counter reads the profiler intervals store — the
    legacy counters are derived views, not parallel instrumentation:
    busy ≡ Σ device_step exactly, staging ≡ Σ host_stage + Σ ring_put."""
    be = DeviceFleetBackend(capacity=128, max_batch=1 << 20, pump_mode=True)
    assert profiler.arm(60_000)
    busy0 = be.pump_busy_s
    stage0 = be.flush_totals["staging_s"]
    _pump_rounds(be, rounds=5)
    ivs = profiler.intervals()
    step_sum = sum(iv.dur for iv in ivs if iv.lane == "device_step")
    stage_sum = sum(
        iv.dur for iv in ivs if iv.lane in ("host_stage", "ring_put")
    )
    assert step_sum > 0 and stage_sum > 0
    assert be.pump_busy_s - busy0 == pytest.approx(step_sum, abs=1e-12)
    assert be.flush_totals["staging_s"] - stage0 == pytest.approx(
        stage_sum, abs=1e-9
    )
    # Fleet-side routing has its own bucket now — staging_s no longer
    # hides a component the timeline cannot see.
    assert "routing_s" in be.flush_totals


def test_legacy_counters_are_derived_views_oneshot():
    """The one-shot flush path holds the same derived-view equivalence
    (its host_stage/dispatch intervals bracket apply_sparse)."""
    be = DeviceFleetBackend(
        capacity=128, max_batch=1 << 20, pump_mode=False
    )
    assert profiler.arm(60_000)
    stage0 = be.flush_totals["staging_s"]
    for r in range(3):
        _feed(be, r)
        be.flush()
    be.collect_now()
    ivs = profiler.intervals()
    stage_sum = sum(iv.dur for iv in ivs if iv.lane == "host_stage")
    assert stage_sum > 0
    assert be.flush_totals["staging_s"] - stage0 == pytest.approx(
        stage_sum, abs=1e-9
    )


# ---------------------------------------------------------------------------
# summarize(): the host-tax attribution


def test_summarize_decomposes_the_window():
    """A captured pump window decomposes into named lanes + the derived
    loop_other gap (coverage ≈ 1 by construction — asserted ≥ 0.95, the
    bench bar), reports per-boxcar host tax percentiles, and derives the
    device-idle fraction the bench reconciles with
    serving_pump_device_idle_frac."""
    be = DeviceFleetBackend(capacity=128, max_batch=1 << 20, pump_mode=True)
    assert profiler.arm(60_000)
    busy0 = be.pump_busy_s
    t0 = time.perf_counter()
    _pump_rounds(be, rounds=5)
    wall = time.perf_counter() - t0
    s = profiler.summarize()
    assert s["boxcars"] == 5
    assert s["coverage_frac"] >= 0.95
    for lane in ("host_stage", "ring_put", "dispatch", "device_step",
                 "scan_consume"):
        assert s["lanes_ms"].get(lane, 0.0) > 0.0, (lane, s["lanes_ms"])
    tax = s["serving_host_tax_ms"]
    assert tax["p99"] >= tax["p50"] >= 0.0
    # Two instruments, one truth: the timeline-derived idle fraction
    # reconciles with the legacy busy-union instrument over the same
    # workload (the window extents differ slightly — tolerance).
    legacy_idle = max(0.0, 1.0 - (be.pump_busy_s - busy0) / wall)
    assert s["device_idle_frac"] == pytest.approx(legacy_idle, abs=0.05)


def test_capture_window_self_disarms():
    """A bounded window disarms itself once elapsed even if no surface
    calls disarm() — a crashed /profilez client cannot leave the
    profiler armed forever."""
    assert profiler.arm(1.0)  # 1 ms window
    assert profiler.enabled()
    time.sleep(0.01)
    now = time.perf_counter()
    profiler.record("gc_pause", now - 1e-4, now)  # past the deadline
    assert not profiler.enabled()


def test_arm_fault_is_counted_and_absorbed():
    """The ``profiler.arm`` site's contract (the journal.dump absorb
    shape): a failed arm is counted
    (retry_attempts_total{profiler.arm,fallback}) and returns False —
    never raised into the caller — and the next arm works."""
    faults.arm("profiler.arm", faults.FailN(1))
    assert profiler.arm(100) is False
    faults.disarm()
    c = metrics.REGISTRY.get("retry_attempts_total")
    assert c.value(site="profiler.arm", outcome="fallback") == 1
    assert not profiler.enabled()
    assert profiler.arm(100) is True


# ---------------------------------------------------------------------------
# /profilez over a real websocket server


def test_profilez_bounded_capture_over_real_server():
    """GET /profilez?duration_ms=N arms a bounded window, captures the
    traffic served DURING it, returns valid Perfetto JSON, and leaves
    the profiler disarmed."""
    from fluidframework_tpu.service.network_server import FluidNetworkServer

    svc = PipelineFluidService(n_partitions=2)
    conn = svc.connect("pz-doc")
    srv = FluidNetworkServer(service=svc)
    srv.start()
    try:
        result: dict = {}

        def fetch():
            result["body"] = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/profilez?duration_ms=400",
                timeout=10,
            ).read()

        t = threading.Thread(target=fetch)
        t.start()
        # Drive serving traffic while the window is armed (the profiler
        # is process-global; these submits run the instrumented seams).
        deadline = time.monotonic() + 3
        c0 = 1
        while not profiler.enabled() and time.monotonic() < deadline:
            time.sleep(0.005)
        for _ in range(4):
            conn.submit_frame(_one_frame(conn, svc, "pz-doc", c0=c0))
            c0 += 3
            svc.pump()
        svc.flush_device()
        t.join(10)
        assert "body" in result, "profilez request did not complete"
        doc = json.loads(result["body"])
        names = {
            e["name"] for e in doc["traceEvents"] if e["ph"] == "X"
        }
        assert {"ticket", "host_stage", "dispatch"} <= names, names
        assert not profiler.enabled(), "capture must disarm after window"
    finally:
        srv.stop()


def test_profilez_rejects_nonfinite_window_and_serializes_captures():
    """Two edge contracts on the untrusted surface: a NaN/inf
    duration_ms is rejected with 400 (NaN slips through min/max clamps
    and would defeat the self-disarm deadline AND hang the handler's
    sleep), and a second capture request while one is armed gets 409 —
    a concurrent arm would reset the ring mid-capture and the first
    disarm would truncate the second window."""
    from fluidframework_tpu.service.network_server import FluidNetworkServer

    svc = PipelineFluidService(n_partitions=2)
    srv = FluidNetworkServer(service=svc)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        for bad in ("nan", "inf", "-inf", "bogus"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"{base}/profilez?duration_ms={bad}", timeout=5
                )
            assert ei.value.code == 400, bad
            assert not profiler.enabled(), bad
        # The in-process arm refuses non-finite windows too (counted,
        # absorbed — never armed-forever).
        assert profiler.arm(float("nan")) is False
        assert not profiler.enabled()
        result: dict = {}

        def fetch():
            result["body"] = urllib.request.urlopen(
                f"{base}/profilez?duration_ms=600", timeout=10
            ).read()

        t = threading.Thread(target=fetch)
        t.start()
        deadline = time.monotonic() + 3
        while not profiler.enabled() and time.monotonic() < deadline:
            time.sleep(0.005)
        assert profiler.enabled()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{base}/profilez?duration_ms=5", timeout=5
            )
        assert ei.value.code == 409
        assert profiler.enabled(), "409 must not disturb the live capture"
        t.join(10)
        json.loads(result["body"])  # the first capture completes intact
    finally:
        srv.stop()


def test_arm_honors_long_inprocess_windows():
    """In-process callers (benches) may arm windows longer than the
    /profilez clamp — only the untrusted HTTP surface clamps to
    MAX_WINDOW_MS; a bench's 120s capture must not self-disarm after
    10s mid-workload."""
    assert profiler.arm(120_000)
    now = time.perf_counter()
    assert profiler.PROFILER._until - now > 100.0
    profiler.record("gc_pause", now, now + 0.001)  # well inside window
    assert profiler.enabled()


def test_profilez_is_not_shed_exempt():
    """The shed-tier contract, the OPPOSITE way from /metrics and
    /debugz: an armed capture allocates, so /profilez 503s with
    Retry-After at SHED_READS and every tier above — while the two
    exempt surfaces stay reachable through the whole walk (the tier-walk
    sibling of the SHED_READS push test)."""
    from fluidframework_tpu.service.admission import Tier
    from fluidframework_tpu.service.network_server import FluidNetworkServer

    svc = PipelineFluidService(n_partitions=2)
    srv = FluidNetworkServer(service=svc)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        body = urllib.request.urlopen(
            f"{base}/profilez?duration_ms=5", timeout=5
        ).read()
        json.loads(body)  # NORMAL tier: capture served
        for tier in (
            Tier.SHED_READS, Tier.THROTTLE_WRITES, Tier.REFUSE_CONNECTIONS
        ):
            svc.overload.force(tier)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"{base}/profilez?duration_ms=5", timeout=5
                )
            assert ei.value.code == 503, tier
            assert ei.value.headers.get("Retry-After") is not None, tier
            assert not profiler.enabled(), tier  # nothing armed
            # The exempt observability pair still serves at this tier.
            assert urllib.request.urlopen(
                f"{base}/metrics", timeout=5
            ).status == 200
            assert urllib.request.urlopen(
                f"{base}/debugz", timeout=5
            ).status == 200
        svc.overload.force(Tier.NORMAL)  # walk back down...
        svc.overload.force(None)  # ...and unpin
        body = urllib.request.urlopen(
            f"{base}/profilez?duration_ms=5", timeout=5
        ).read()
        json.loads(body)  # back to NORMAL: capture served again
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Runtime watchdogs


def test_loop_stall_sentinel_catches_a_blocking_call():
    """An injected blocking sleep on the socket loop overshoots the
    sentinel's expected tick: the stall is counted, journaled BY NAME
    (loop.stall), exported on the event_loop_lag_ms gauge, and — with a
    capture armed — recorded on the loop_lag timeline lane."""
    import asyncio

    from fluidframework_tpu.service.network_server import FluidNetworkServer

    svc = PipelineFluidService(n_partitions=2)
    srv = FluidNetworkServer(service=svc)
    srv.loop_lag_threshold_ms = 60.0
    srv.start()
    try:
        deadline = time.monotonic() + 5
        while srv.lag_ticks < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert srv.lag_ticks >= 2, "sentinel never ticked"
        assert profiler.arm(5000)

        async def block():
            time.sleep(0.15)  # a synchronous stall ON the loop

        asyncio.run_coroutine_threadsafe(block(), srv._loop).result(5)
        deadline = time.monotonic() + 5
        while srv.stalls_seen == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert srv.stalls_seen >= 1, "blocking call not caught"
        stalls = [
            e for e in journal.JOURNAL.events() if e.kind == "loop.stall"
        ]
        assert stalls, "stall must land in the flight recorder"
        assert dict(stalls[0].detail)["lag_ms"] >= 60.0
        lag_ivs = [
            iv for iv in profiler.intervals() if iv.lane == "loop_lag"
        ]
        assert lag_ivs and lag_ivs[0].dur >= 0.06
        # The gauge exists and was fed (healthy ticks may have already
        # overwritten the stall value — the journal carries the event).
        assert metrics.REGISTRY.get("event_loop_lag_ms") is not None
    finally:
        profiler.disarm()
        srv.stop()


def test_gc_pause_hooks_feed_metrics_and_timeline():
    """gc.callbacks pause hooks: every collection lands on the
    gc_pause_ms histogram and the gen-labelled gc_pauses_total counter,
    and on the gc_pause timeline lane while a capture is armed. The
    callback itself is LOCK-FREE by contract (a collection can trigger
    mid-allocation inside a metrics or ring lock on the same thread —
    taking any lock there deadlocks the thread against itself): it only
    buffers, and the read surfaces / the lag sentinel drain."""
    fresh = profiler.install_gc_hooks()
    try:
        assert profiler.arm(60_000)
        gc.collect(2)
        # The buffered pause is invisible until a drain runs (the
        # callback touched no metric); intervals() drains implicitly.
        pauses = [
            iv for iv in profiler.intervals() if iv.lane == "gc_pause"
        ]
        assert pauses and pauses[0].dur >= 0.0
        hist = metrics.REGISTRY.get("gc_pause_ms")
        assert hist is not None and hist.count() >= 1
        counter = metrics.REGISTRY.get("gc_pauses_total")
        assert counter is not None and counter.value(gen="2") >= 1
        # A drained buffer is empty; a second explicit drain is a no-op.
        assert profiler.drain_gc_events() == 0
        # Idempotent install: a second install is a no-op.
        assert profiler.install_gc_hooks() is False
    finally:
        profiler.disarm()
        if fresh:
            profiler.uninstall_gc_hooks()
