"""Snapshot/back-compat golden tests (SURVEY.md §4.8).

Reference: ``packages/test/snapshots`` replays stored op logs and validates
the generated summaries against golden files per format version
(``validateSnapshots.ts``). Here: a canonical deterministic session's op
log and its summary are committed under ``tests/goldens/``; every build
must (a) replay the log to the same observable state and (b) produce a
byte-identical summary, so any unnoticed format/semantic drift fails.

Regenerate (after an INTENTIONAL format change):
    python tests/test_snapshot_goldens.py regenerate
"""

import json
import os

import pytest

from fluidframework_tpu.models.shared_map import SharedMap
from fluidframework_tpu.models.shared_string import SharedString
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.local_server import LocalFluidService

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "goldens")


def canonical_session(svc: LocalFluidService) -> ContainerRuntime:
    """A deterministic multi-op session exercising inserts, removes,
    annotates, maps, quorum and summary-relevant state."""
    a = ContainerRuntime(
        svc, "golden", channels=(SharedString("text"), SharedMap("map"))
    )
    b = ContainerRuntime(
        svc, "golden", channels=(SharedString("text"), SharedMap("map"))
    )

    def drain():
        for rt in (a, b):
            rt.flush()
        busy = True
        while busy:
            busy = any(rt.process_incoming() for rt in (a, b))

    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "hello world")
    drain()
    sb.insert_text(5, ",")
    sa.remove_range(0, 1)
    drain()
    sa.insert_text(0, "H")
    sa.annotate(0, 5, 3)
    a.get_channel("map").set("title", "golden doc")
    b.get_channel("map").set("count", 42)
    drain()
    b.get_channel("map").delete("count")
    sb.remove_range(5, 6)
    drain()
    b.disconnect()
    a.send_noop()
    a.process_incoming()
    return a


def generate():
    svc = LocalFluidService()
    a = canonical_session(svc)
    ops = [
        json.loads(
            json.dumps(
                {
                    "seq": m.sequence_number,
                    "cid": m.client_id,
                    "cseq": m.client_sequence_number,
                    "ref": m.reference_sequence_number,
                    "msn": m.minimum_sequence_number,
                    "type": int(m.type),
                    "contents": m.contents,
                },
                sort_keys=True,
            )
        )
        for m in svc._doc("golden").op_log
    ]
    summary = a.summarize()
    text = a.get_channel("text").get_text()
    annos = a.get_channel("text").annotations()
    return {
        "ops": ops,
        "summary": summary,
        "text": text,
        "annotations": annos,
    }


def test_canonical_session_matches_golden():
    with open(os.path.join(GOLDEN_DIR, "golden_session.json")) as f:
        golden = json.load(f)
    got = json.loads(json.dumps(generate(), sort_keys=True))
    want = json.loads(json.dumps(golden, sort_keys=True))
    assert got["text"] == want["text"], "replayed text drifted"
    assert got["annotations"] == want["annotations"]
    assert got["ops"] == want["ops"], (
        "sequenced op stream drifted — protocol/semantic change; regenerate "
        "goldens ONLY if intentional"
    )
    assert got["summary"] == want["summary"], (
        "summary format drifted — breaks loading old documents; regenerate "
        "goldens ONLY if intentional"
    )


def test_golden_summary_still_loads():
    """A summary produced by the golden format must load into a live
    container (back-compat with stored documents)."""
    with open(os.path.join(GOLDEN_DIR, "golden_session.json")) as f:
        golden = json.load(f)
    svc = LocalFluidService()
    handle = svc.store.put_summary(golden["summary"])
    doc = svc._doc("golden2")
    doc.latest_summary = (handle, golden["summary"]["sequence_number"])
    doc.sequencer.seq = golden["summary"]["sequence_number"]
    late = ContainerRuntime(
        svc, "golden2", channels=(SharedString("text"), SharedMap("map"))
    )
    assert late.get_channel("text").get_text() == golden["text"]
    assert late.get_channel("map").get("title") == "golden doc"


if __name__ == "__main__":
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "regenerate":
        os.makedirs(GOLDEN_DIR, exist_ok=True)
        with open(os.path.join(GOLDEN_DIR, "golden_session.json"), "w") as f:
            json.dump(generate(), f, sort_keys=True, indent=1)
        print("goldens regenerated")


def test_r1_format_summary_still_loads():
    """Round-1 summaries (single removers-bitmask lane, no rbits2) must
    keep loading after the writer-mask widening: load_core leaves missing
    lanes at their empty defaults."""
    with open(os.path.join(GOLDEN_DIR, "golden_session_r1.json")) as f:
        golden = json.load(f)
    assert "rbits2" not in golden["summary"]["channels"]["text"]["lanes"]
    svc = LocalFluidService()
    handle = svc.store.put_summary(golden["summary"])
    doc = svc._doc("golden3")
    doc.latest_summary = (handle, golden["summary"]["sequence_number"])
    doc.sequencer.seq = golden["summary"]["sequence_number"]
    rt = ContainerRuntime(
        svc, "golden3", channels=(SharedString("text"), SharedMap("map"))
    )
    assert rt.get_channel("text").get_text() == golden["text"]
    assert rt.get_channel("map").get("title") == "golden doc"
