"""The shipped examples run end-to-end (reference examples/ apps)."""

import os
import subprocess
import sys

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


def run_example(name: str) -> str:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, name)],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout


def test_collab_editor_example():
    out = run_example("collab_editor.py")
    assert "converged text" in out


def test_presence_tracker_example():
    out = run_example("presence_tracker.py")
    assert "transient" in out
