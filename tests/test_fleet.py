"""Fleet capacity lifecycle: pooled blocks + host-driven promotion
(VERDICT r1 #5; reference growth analog mergeTree.ts:1268 updateRoot)."""

import numpy as np
import pytest

from fluidframework_tpu.ops import encode as E
from fluidframework_tpu.ops.segment_state import materialize
from fluidframework_tpu.parallel.fleet import DocFleet
from fluidframework_tpu.protocol.constants import OP_WIDTH
from fluidframework_tpu.testing.oracle import OracleDoc
from fluidframework_tpu.protocol.constants import NO_CLIENT


def grow_stream(n_docs, rounds, k, insert_bias=0.9, seed=0):
    """Per-round op batches that keep documents growing (no trailing
    whole-doc remove), tracked against oracles."""
    rng = np.random.default_rng(seed)
    oracles = [OracleDoc(NO_CLIENT) for _ in range(n_docs)]
    payloads = {}
    seqs = [0] * n_docs
    lens = [0] * n_docs
    next_orig = 1
    batches = []
    for _r in range(rounds):
        ops = np.zeros((n_docs, k, OP_WIDTH), np.int32)
        for d in range(n_docs):
            for i in range(k):
                seqs[d] += 1
                if lens[d] > 4 and rng.random() > insert_bias:
                    a = int(rng.integers(0, lens[d] - 2))
                    op = E.remove(a, a + 2, seq=seqs[d], ref=seqs[d] - 1,
                                  client=int(rng.integers(0, 4)))
                    lens[d] -= 2
                else:
                    n = int(rng.integers(1, 4))
                    payloads[next_orig] = "x" * n
                    op = E.insert(int(rng.integers(0, lens[d] + 1)),
                                  next_orig, n, seq=seqs[d],
                                  ref=seqs[d] - 1,
                                  client=int(rng.integers(0, 4)))
                    next_orig += 1
                    lens[d] += n
                ops[d, i] = op
                oracles[d].apply(op)
        batches.append(ops)
    return batches, oracles, payloads


def test_doc_grows_past_initial_capacity_zero_drops():
    # VERDICT "Done": a load drives docs past their initial capacity with
    # zero dropped ops.
    fleet = DocFleet(n_docs=4, capacity=32, high_water=0.7)
    batches, oracles, payloads = grow_stream(4, rounds=12, k=8)
    for ops in batches:
        stats = fleet.apply(ops)
        assert stats["docs_with_errors"] == 0, stats
        fleet.check_and_migrate()
    assert fleet.migrations >= 4  # every doc outgrew the 32-row tier
    assert max(fleet.pools) > 32
    for d in range(4):
        assert materialize(fleet.doc_state(d), payloads) == oracles[d].text(
            payloads
        )


def test_promotion_preserves_pending_free_slots_and_stats():
    fleet = DocFleet(n_docs=2, capacity=16, high_water=0.6)
    batches, oracles, payloads = grow_stream(2, rounds=6, k=6, seed=3)
    for ops in batches:
        fleet.apply(ops)
        fleet.check_and_migrate()
    stats = fleet.stats()
    assert stats["docs_with_errors"] == 0
    # Vacated slots are reusable: the base pool has free slots now.
    base = fleet.pools[16]
    assert base.free_slot() is not None
    for d in range(2):
        assert materialize(fleet.doc_state(d), payloads) == oracles[d].text(
            payloads
        )


def test_without_migration_capacity_trips():
    # The round-1 failure mode still exists if the lifecycle never runs —
    # pinning that the migration is what prevents it.
    fleet = DocFleet(n_docs=1, capacity=16, high_water=0.7)
    batches, _o, _p = grow_stream(1, rounds=10, k=8, seed=1)
    errs = 0
    for ops in batches:
        stats = fleet.apply(ops)  # no check_and_migrate
        errs = stats["docs_with_errors"]
    assert errs == 1  # ERR_CAPACITY tripped without the lifecycle


def test_compaction_runs_per_pool():
    fleet = DocFleet(n_docs=2, capacity=32, high_water=0.7)
    batches, oracles, payloads = grow_stream(
        2, rounds=8, k=6, insert_bias=0.6, seed=5
    )
    for ops in batches:
        # Advance the window so compaction has tombstones to reclaim.
        ops[:, -1, 9] = ops[:, -1, 3]  # F_MSN := F_SEQ on the last op
        for d in range(2):
            oracles[d].min_seq = int(ops[d, -1, 3])
        fleet.apply(ops)
        fleet.compact()
        fleet.check_and_migrate()
    assert fleet.stats()["docs_with_errors"] == 0
    for d in range(2):
        assert materialize(fleet.doc_state(d), payloads) == oracles[d].text(
            payloads
        )


def test_apply_sparse_matches_dense_and_reads_one_doc():
    """The gathered serving-path staging (`apply_sparse`: upload only the
    busy channels' rows + slot indices, scatter on device) produces
    byte-identical state to the dense `apply`, including across tier
    promotions, and `doc_state` reads one document without pulling the
    pool (VERDICT r3 Weak #3)."""
    dense = DocFleet(n_docs=5, capacity=16, high_water=0.7)
    sparse = DocFleet(n_docs=5, capacity=16, high_water=0.7)
    batches, oracles, payloads = grow_stream(5, rounds=6, k=6, seed=7)
    rng = np.random.default_rng(3)
    for ops in batches:
        # A random subset of docs is busy each round; the rest get no rows
        # at all on the sparse path (the dense path ships their zeros).
        busy = sorted(rng.choice(5, size=int(rng.integers(1, 6)),
                                 replace=False))
        dense_ops = np.zeros_like(ops)
        dense_ops[busy] = ops[busy]
        dense.apply(dense_ops)
        sparse.apply_sparse(list(map(int, busy)), ops[busy])
        for f in (dense, sparse):
            f.compact()
            f.check_and_migrate()
    from fluidframework_tpu.ops.segment_state import SEGMENT_LANES

    assert dense.stats() == sparse.stats()
    for d in range(5):
        s1, s2 = dense.doc_state(d), sparse.doc_state(d)
        for lane in SEGMENT_LANES:
            assert np.array_equal(getattr(s1, lane), getattr(s2, lane)), (
                d, lane,
            )
        for s in ("count", "min_seq", "cur_seq", "self_client", "err"):
            assert int(getattr(s1, s)) == int(getattr(s2, s)), (d, s)


def test_apply_sparse_pads_and_drops_out_of_range():
    """B pads to a pow2 bucket; padding rows carry an out-of-range slot
    index and must scatter to nowhere (not corrupt slot 0)."""
    fleet = DocFleet(n_docs=3, capacity=16, high_water=0.9)
    ops = np.zeros((1, 8, OP_WIDTH), np.int32)
    ops[0, 0] = E.insert(0, 1, 3, seq=1, ref=0, client=0)
    payloads = {1: "abc"}
    fleet.apply_sparse([1], ops)  # B=1, no pad needed
    ops2 = np.zeros((3, 8, OP_WIDTH), np.int32)
    ops2[0, 0] = E.insert(0, 2, 2, seq=2, ref=1, client=0)
    ops2[1, 0] = E.insert(0, 3, 1, seq=1, ref=0, client=0)
    ops2[2, 0] = E.insert(0, 4, 1, seq=1, ref=0, client=0)
    payloads.update({2: "de", 3: "f", 4: "g"})
    fleet.apply_sparse([1, 0, 2], ops2)  # B=3 pads to 4
    assert materialize(fleet.doc_state(1), payloads) == "deabc"
    assert materialize(fleet.doc_state(0), payloads) == "f"
    assert materialize(fleet.doc_state(2), payloads) == "g"
    assert fleet.stats()["docs_with_errors"] == 0


def test_stale_scan_dropped_for_reassigned_slots():
    """A health scan begun before a slot's occupant changed must not
    attribute the departed doc's count/err to the new occupant
    (ADVICE r4: placement generation per slot)."""
    fleet = DocFleet(1, capacity=8, max_capacity=64)
    # Fill doc 0 hot (above high water in the base tier).
    ops = np.zeros((1, 8, OP_WIDTH), np.int32)
    for i in range(7):
        ops[0, i] = E.insert(0, i + 1, 1, seq=i + 1, ref=i, client=0)
    fleet.apply(ops)
    token = fleet.begin_scan()  # snapshot: slot 0 hot, gen G
    # Occupant changes: doc 0 promotes out, doc 1 lands in its slot.
    fleet.check_and_migrate()
    assert fleet.placement[0][0] == 16
    d1 = fleet.add_doc()
    assert fleet.placement[d1] == (8, 0)  # reused the vacated slot
    scans = fleet.finish_scan(token)
    # The stale column (old occupant's count 7) is zeroed.
    assert scans[8][0][0] == 0
    # Consuming the stale scan must not re-promote the NEW occupant.
    promoted = fleet.check_and_migrate({c: s[0] for c, s in scans.items()})
    assert d1 not in promoted
