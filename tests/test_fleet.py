"""Fleet capacity lifecycle: pooled blocks + host-driven promotion
(VERDICT r1 #5; reference growth analog mergeTree.ts:1268 updateRoot)."""

import numpy as np
import pytest

from fluidframework_tpu.ops import encode as E
from fluidframework_tpu.ops.segment_state import materialize
from fluidframework_tpu.parallel.fleet import DocFleet
from fluidframework_tpu.protocol.constants import OP_WIDTH
from fluidframework_tpu.testing.oracle import OracleDoc
from fluidframework_tpu.protocol.constants import NO_CLIENT


def grow_stream(n_docs, rounds, k, insert_bias=0.9, seed=0):
    """Per-round op batches that keep documents growing (no trailing
    whole-doc remove), tracked against oracles."""
    rng = np.random.default_rng(seed)
    oracles = [OracleDoc(NO_CLIENT) for _ in range(n_docs)]
    payloads = {}
    seqs = [0] * n_docs
    lens = [0] * n_docs
    next_orig = 1
    batches = []
    for _r in range(rounds):
        ops = np.zeros((n_docs, k, OP_WIDTH), np.int32)
        for d in range(n_docs):
            for i in range(k):
                seqs[d] += 1
                if lens[d] > 4 and rng.random() > insert_bias:
                    a = int(rng.integers(0, lens[d] - 2))
                    op = E.remove(a, a + 2, seq=seqs[d], ref=seqs[d] - 1,
                                  client=int(rng.integers(0, 4)))
                    lens[d] -= 2
                else:
                    n = int(rng.integers(1, 4))
                    payloads[next_orig] = "x" * n
                    op = E.insert(int(rng.integers(0, lens[d] + 1)),
                                  next_orig, n, seq=seqs[d],
                                  ref=seqs[d] - 1,
                                  client=int(rng.integers(0, 4)))
                    next_orig += 1
                    lens[d] += n
                ops[d, i] = op
                oracles[d].apply(op)
        batches.append(ops)
    return batches, oracles, payloads


def test_doc_grows_past_initial_capacity_zero_drops():
    # VERDICT "Done": a load drives docs past their initial capacity with
    # zero dropped ops.
    fleet = DocFleet(n_docs=4, capacity=32, high_water=0.7)
    batches, oracles, payloads = grow_stream(4, rounds=12, k=8)
    for ops in batches:
        stats = fleet.apply(ops)
        assert stats["docs_with_errors"] == 0, stats
        fleet.check_and_migrate()
    assert fleet.migrations >= 4  # every doc outgrew the 32-row tier
    assert max(fleet.pools) > 32
    for d in range(4):
        assert materialize(fleet.doc_state(d), payloads) == oracles[d].text(
            payloads
        )


def test_promotion_preserves_pending_free_slots_and_stats():
    fleet = DocFleet(n_docs=2, capacity=16, high_water=0.6)
    batches, oracles, payloads = grow_stream(2, rounds=6, k=6, seed=3)
    for ops in batches:
        fleet.apply(ops)
        fleet.check_and_migrate()
    stats = fleet.stats()
    assert stats["docs_with_errors"] == 0
    # Vacated slots are reusable: the base pool has free slots now.
    base = fleet.pools[16]
    assert base.free_slot() is not None
    for d in range(2):
        assert materialize(fleet.doc_state(d), payloads) == oracles[d].text(
            payloads
        )


def test_without_migration_capacity_trips():
    # The round-1 failure mode still exists if the lifecycle never runs —
    # pinning that the migration is what prevents it.
    fleet = DocFleet(n_docs=1, capacity=16, high_water=0.7)
    batches, _o, _p = grow_stream(1, rounds=10, k=8, seed=1)
    errs = 0
    for ops in batches:
        stats = fleet.apply(ops)  # no check_and_migrate
        errs = stats["docs_with_errors"]
    assert errs == 1  # ERR_CAPACITY tripped without the lifecycle


def test_compaction_runs_per_pool():
    fleet = DocFleet(n_docs=2, capacity=32, high_water=0.7)
    batches, oracles, payloads = grow_stream(
        2, rounds=8, k=6, insert_bias=0.6, seed=5
    )
    for ops in batches:
        # Advance the window so compaction has tombstones to reclaim.
        ops[:, -1, 9] = ops[:, -1, 3]  # F_MSN := F_SEQ on the last op
        for d in range(2):
            oracles[d].min_seq = int(ops[d, -1, 3])
        fleet.apply(ops)
        fleet.compact()
        fleet.check_and_migrate()
    assert fleet.stats()["docs_with_errors"] == 0
    for d in range(2):
        assert materialize(fleet.doc_state(d), payloads) == oracles[d].text(
            payloads
        )
