"""Service deployable: layered config, entrypoint, smoke client.

Reference: server/routerlicious/Dockerfile + config/config.json (nconf
layering) + the docker-compose single-box deployment. Docker itself is
exercised when available (CI images without a daemon skip that case and
still verify the whole path in-proc: config -> server_main -> sockets ->
smoke client -> device-served read)."""

import json
import os
import shutil
import subprocess
import sys

import pytest

from fluidframework_tpu.service.server_main import (
    DEFAULTS,
    build_server,
    load_config,
)
from fluidframework_tpu.service.smoke_client import run as smoke_run


def test_config_layering(tmp_path):
    p = tmp_path / "config.json"
    p.write_text(json.dumps({"port": 9999, "partitions": 2}))
    cfg = load_config(str(p), env={"FLUID_PARTITIONS": "8"})
    assert cfg["port"] == 9999  # file over defaults
    assert cfg["partitions"] == 8  # env over file
    assert cfg["device_backend"] is True  # defaults fill the rest
    cfg2 = load_config(str(p), env={}, overrides={"port": 1234})
    assert cfg2["port"] == 1234  # CLI overrides everything


def test_config_rejects_unknown_keys(tmp_path):
    p = tmp_path / "config.json"
    p.write_text(json.dumps({"prot": 1}))
    with pytest.raises(ValueError):
        load_config(str(p), env={})


def test_repo_config_file_is_valid():
    root = os.path.join(os.path.dirname(__file__), "..")
    cfg = load_config(os.path.join(root, "config", "config.json"), env={})
    assert set(cfg) == set(DEFAULTS)


def test_entrypoint_serves_smoke_client():
    """The deployable path in-proc: build_server from the repo config
    (ephemeral port), run the compose smoke client against it."""
    root = os.path.join(os.path.dirname(__file__), "..")
    cfg = load_config(os.path.join(root, "config", "config.json"), env={})
    cfg.update(host="127.0.0.1", port=0)  # ephemeral
    srv = build_server(cfg)
    srv.start()
    try:
        assert smoke_run("127.0.0.1", srv.port, timeout=30.0) == 0
    finally:
        srv.stop()


def test_server_main_process_starts_and_stops(tmp_path):
    """The actual CLI process comes up, prints its listening line, and
    shuts down cleanly on SIGTERM (what the container runs)."""
    p = tmp_path / "config.json"
    p.write_text(json.dumps({"host": "127.0.0.1", "port": 0}))
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        os.path.join(os.path.dirname(__file__), "..")
        + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    # Pin the subprocess to CPU: under full-suite load the tunneled
    # accelerator backend's remote compiles are intermittent (the same
    # failure mode the examples had); server_main honors this env.
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.server_main",
         "--config", str(p)],
        stdout=subprocess.PIPE, env=env, text=True,
    )
    try:
        line = proc.stdout.readline()
        info = json.loads(line)
        assert info["event"] == "listening" and info["port"] > 0
        assert smoke_run("127.0.0.1", info["port"], timeout=30.0) == 0
        proc.terminate()
        assert proc.wait(timeout=15) == 0
    finally:
        if proc.poll() is None:
            proc.kill()


docker = shutil.which("docker")


@pytest.mark.skipif(
    docker is None, reason="docker unavailable in this environment"
)
def test_docker_compose_smoke():  # pragma: no cover - needs a daemon
    root = os.path.join(os.path.dirname(__file__), "..")
    res = subprocess.run(
        [docker, "compose", "up", "--build", "--abort-on-container-exit",
         "--exit-code-from", "smoke"],
        cwd=root, capture_output=True, timeout=900,
    )
    subprocess.run([docker, "compose", "down"], cwd=root, capture_output=True)
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
