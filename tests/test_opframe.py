"""The batched binary client op wire (protocol/opframe.py).

Reference: the socket submit path — driver-base
``documentDeltaConnection.ts`` → alfred → deli ``ticket()``
(``lambdas/src/deli/lambda.ts:742``). Frames must be semantically
invisible: the same op stream shipped per-op (JSON wire) or batched
(binary frame wire) produces identical sequencing, identical device
state, and identical client-visible messages.
"""

import numpy as np
import pytest

from fluidframework_tpu.models.shared_map import SharedMap
from fluidframework_tpu.models.shared_string import SharedString
from fluidframework_tpu.protocol.constants import (
    F_CLIENT,
    F_MSN,
    F_REF,
    F_SEQ,
    OP_WIDTH,
)
from fluidframework_tpu.protocol.opframe import OpFrame, SeqFrame
from fluidframework_tpu.protocol.types import DocumentMessage, MessageType
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.pipeline import PipelineFluidService
from fluidframework_tpu.service.sequencer import DocumentSequencer, FrameTicket

MINT = 1 << 14  # _MINT_STRIDE


def _frame(conn, kinds, a, b, tv, csn0, ref):
    return OpFrame.build("s", kinds, a, b, tv, csn0, ref)


def test_codec_roundtrip():
    f = OpFrame.build(
        "chan/1", ["ins", "rem", "ann", "ins"], [0, 1, 0, 2], [7, 3, 2, 9],
        ["héllo", None, 5, "x\x00y"], csn0=4, ref=11,
    )
    g = OpFrame.decode(f.encode())
    assert g.address == "chan/1" and g.csn0 == 4
    np.testing.assert_array_equal(g.rows, f.rows)
    assert g.texts == ("héllo", "x\x00y")

    rows = np.array(f.rows)
    rows[:, F_SEQ] = 100 + np.arange(4)
    sf = SeqFrame("chan/1", 3, 4, rows, f.texts, 123.5)
    sg = SeqFrame.decode(sf.encode())
    assert (sg.client_id, sg.csn0, sg.timestamp) == (3, 4, 123.5)
    np.testing.assert_array_equal(sg.rows, rows)
    assert sg.first_seq == 100 and sg.last_seq == 103


def test_from_messages_lowering():
    msgs = [
        DocumentMessage(1, 5, MessageType.OPERATION,
                        {"address": "s", "contents": {"k": "ins", "pos": 0,
                                                      "text": "ab", "orig": 9}}),
        DocumentMessage(2, 5, MessageType.OPERATION,
                        {"address": "s", "contents": {"k": "rem", "start": 0,
                                                      "end": 1}}),
    ]
    f = OpFrame.from_messages(msgs)
    assert f is not None and f.n == 2 and f.csn0 == 1
    assert f.texts == ("ab",)
    # Mixed addresses / non-contiguous csns are not frame-eligible.
    bad = [msgs[0], DocumentMessage(3, 5, MessageType.OPERATION,
                                    {"address": "s", "contents":
                                     {"k": "rem", "start": 0, "end": 1}})]
    assert OpFrame.from_messages(bad) is None


class TestTicketFrameParity:
    """ticket_frame(frame) must stamp exactly what n ticket() calls do."""

    def _mk_pair(self):
        a, b = DocumentSequencer("d"), DocumentSequencer("d")
        for s in (a, b):
            s.join()
            s.join()
        return a, b

    def _op(self, csn, ref):
        return DocumentMessage(csn, ref, MessageType.OPERATION, {"x": csn})

    def test_stamps_match_per_op_path(self):
        per_op, framed = self._mk_pair()
        csns = list(range(1, 9))
        refs = [2, 2, 2, 3, 3, 4, 4, 4]
        seqs, msns = [], []
        for c, r in zip(csns, refs):
            m = per_op.ticket(0, self._op(c, r))
            seqs.append(m.sequence_number)
            msns.append(m.minimum_sequence_number)
        res = framed.ticket_frame(0, 1, 8, refs)
        assert isinstance(res, FrameTicket)
        assert res.drop == 0 and res.m == 8
        assert list(range(res.seq0, res.seq0 + 8)) == seqs
        assert res.msn.tolist() == msns
        ca, cb = per_op.checkpoint(), framed.checkpoint()
        assert (ca.sequence_number, ca.minimum_sequence_number) == (
            cb.sequence_number, cb.minimum_sequence_number)
        strip = lambda cs: [
            {k: v for k, v in c.items() if k != "last_seen"} for c in cs
        ]
        assert strip(ca.clients) == strip(cb.clients)

    def test_dup_prefix_drops(self):
        per_op, framed = self._mk_pair()
        for c in (1, 2, 3):
            per_op.ticket(0, self._op(c, 2))
            framed.ticket(0, self._op(c, 2))
        # Replay: frame csn 2..5 — 2,3 are dups, 4,5 ticket.
        res = framed.ticket_frame(0, 2, 4, [2, 2, 2, 2])
        assert isinstance(res, FrameTicket)
        assert res.drop == 2 and res.m == 2
        m4 = per_op.ticket(0, self._op(4, 2))
        m5 = per_op.ticket(0, self._op(5, 2))
        assert [res.seq0, res.seq0 + 1] == [m4.sequence_number,
                                            m5.sequence_number]
        assert res.msn.tolist() == [m4.minimum_sequence_number,
                                    m5.minimum_sequence_number]
        # All-dup frame: silently dropped, like per-op None.
        assert framed.ticket_frame(0, 1, 5, [2] * 5) is None

    def test_gap_nacks(self):
        _, framed = self._mk_pair()
        framed.ticket(0, self._op(1, 2))
        res = framed.ticket_frame(0, 3, 2, [2, 2])
        assert res.content_code == 400
        assert res.client_sequence_number == 3
        # Nack consumed nothing: csn 2 still tickets.
        assert framed.ticket(0, self._op(2, 2)) is not None

    def test_stale_ref_prefix_and_trailing_nack(self):
        per_op, framed = self._mk_pair()
        # Advance MSN past 0: both clients ref 3 after some ops.
        for s in (per_op, framed):
            s.ticket(0, self._op(1, 2))
            s.ticket(1, self._op(1, 3))
            s.ticket(0, self._op(2, 3))
        assert framed.min_seq == per_op.min_seq > 0
        floor = framed.min_seq
        # Frame where op 2 has a stale ref: ops 0-1 ticket, 2+ nack.
        refs = [floor, floor + 1, floor - 1, floor + 1]
        res = framed.ticket_frame(0, 3, 4, refs)
        assert isinstance(res, FrameTicket)
        assert res.m == 2 and res.trailing_nack is not None
        assert res.trailing_nack.client_sequence_number == 5
        # Per-op path: 2 tickets then a stale nack at csn 5.
        m3 = per_op.ticket(0, self._op(3, refs[0]))
        m4 = per_op.ticket(0, self._op(4, refs[1]))
        n5 = per_op.ticket(0, self._op(5, refs[2]))
        assert [m3.sequence_number, m4.sequence_number] == [res.seq0,
                                                            res.seq0 + 1]
        assert n5.content_code == 400
        # Entirely-stale frame nacks up front.
        res2 = framed.ticket_frame(0, 5, 2, [floor - 1, floor])
        assert res2.content_code == 400 and res2.client_sequence_number == 5

    def test_non_monotone_refs_match_per_op_msn_floor(self):
        """Op i must clear the MSN established BY op i-1 (code-review r5):
        refs [hi, lo] may not publish min_seq above the sender's own ref."""
        per_op, framed = self._mk_pair()
        # Other client parks its ref high.
        per_op.ticket(1, self._op(1, 2))
        framed.ticket(1, self._op(1, 2))
        for s in (per_op, framed):
            s.clients[1].ref_seq = 200
        refs = [100, 5]
        m0 = per_op.ticket(0, self._op(1, refs[0]))
        n1 = per_op.ticket(0, self._op(2, refs[1]))
        assert m0 is not None and n1.content_code == 400
        res = framed.ticket_frame(0, 1, 2, refs)
        assert isinstance(res, FrameTicket)
        assert res.m == 1 and res.trailing_nack is not None
        assert res.msn.tolist() == [m0.minimum_sequence_number]
        assert framed.min_seq == per_op.min_seq
        assert framed.clients[0].ref_seq == per_op.clients[0].ref_seq == 100

    def test_expansion_carries_batch_atomicity_marks(self):
        """A frame is one client batch: expansion re-synthesizes
        batchBegin/batchEnd so inbound batch atomicity survives."""
        f = OpFrame.build("s", ["ins", "ins", "ins"], [0, 1, 2],
                          [1, 2, 3], ["a", "b", "c"], csn0=1, ref=0)
        rows = np.array(f.rows)
        rows[:, F_SEQ] = 10 + np.arange(3)
        sf = SeqFrame("s", 0, 1, rows, f.texts, 0.0)
        msgs = sf.messages()
        assert msgs[0].metadata == {"batchBegin": True}
        assert msgs[1].metadata is None
        assert msgs[2].metadata == {"batchEnd": True}
        assert sf.message(0).metadata == {"batchBegin": True}
        assert sf.message(2).metadata == {"batchEnd": True}
        # Tail expansion still closes the batch.
        assert sf.messages(2)[-1].metadata == {"batchEnd": True}
        # Single-op frames are not batches.
        one = SeqFrame("s", 0, 1, rows[:1], ("a",), 0.0)
        assert one.messages()[0].metadata is None

    def test_unknown_and_readonly_clients(self):
        s = DocumentSequencer("d")
        assert s.ticket_frame(7, 1, 1, [0]).content_code == 400
        s.join(mode="read")
        assert s.ticket_frame(0, 1, 1, [0]).content_code == 403


class TestFramePipeline:
    def _mint(self, conn, i):
        return conn.conn_no * MINT + i

    def test_device_parity_and_client_convergence(self):
        """One writer ships frames; a normal container client converges;
        the device replica matches; catch-up reads expand frames."""
        svc = PipelineFluidService(n_partitions=2)
        reader = ContainerRuntime(svc, "doc", channels=(SharedString("s"),
                                                        SharedMap("m")))
        conn = svc.connect("doc")
        ref = svc.doc_head("doc")
        texts = ["ab", "cd", "ef"]
        f1 = OpFrame.build(
            "s", ["ins", "ins", "ins"], [0, 2, 4],
            [self._mint(conn, 1), self._mint(conn, 2), self._mint(conn, 3)],
            texts, csn0=1, ref=ref,
        )
        conn.submit_frame(f1)
        svc.pump()
        svc.flush_device()
        assert svc.device_text("doc", "s") == "abcdef"
        # Remove through a second frame.
        f2 = OpFrame.build("s", ["rem"], [1], [3], [None], csn0=4,
                           ref=svc.doc_head("doc"))
        conn.submit_frame(f2)
        svc.flush_device()
        assert svc.device_text("doc", "s") == "adef"
        # The container client saw the frames expanded and converged.
        while reader.process_incoming():
            pass
        assert reader.get_channel("s").get_text() == "adef"
        # Catch-up: a fresh connection backfills per-op messages.
        late = svc.connect("doc")
        ops = [m for m in late.inbox
               if getattr(m, "type", None) == MessageType.OPERATION]
        assert len(ops) == 4
        assert ops[0].contents["contents"]["text"] == "ab"
        # Ranged read expands too.
        ranged = svc.ops_range("doc", ops[0].sequence_number,
                               ops[-1].sequence_number)
        assert [m.sequence_number for m in ranged] == [
            m.sequence_number for m in ops]

    def test_replay_idempotence_at_device(self):
        """Redelivering a frame (at-least-once) must not double-apply."""
        svc = PipelineFluidService(n_partitions=1)
        conn = svc.connect("doc")
        f = OpFrame.build("s", ["ins"], [0], [self._mint(conn, 1)], ["x"],
                          csn0=1, ref=svc.doc_head("doc"))
        conn.submit_frame(f)
        svc.flush_device()
        sf_records = [
            r.value for r in svc.log.read("deltas", 0, 0)
            if isinstance(r.value, dict) and r.value.get("t") == "seqframe"
        ]
        assert sf_records
        # Live redelivery straight into the backend.
        svc.device.enqueue_frame("doc", sf_records[0]["frame"])
        svc.flush_device()
        assert svc.device_text("doc", "s") == "x"
        assert svc.device.stats()["ops_applied"] == 1

    def test_stale_ref_frame_nacks_then_fresh_ref_tickets(self):
        """Regression: deli must ticket against the frame's REF column,
        not its csn column — a frame with fresh refs and old csns (the
        nack-recovery resubmission shape) must sequence."""
        svc = PipelineFluidService(n_partitions=1)
        a = svc.connect("doc")
        b = svc.connect("doc")
        # March MSN forward: both clients submit with advancing refs.
        for i in range(1, 7):
            for conn in (a, b):
                head = svc.doc_head("doc")
                f = OpFrame.build(
                    "s", ["ins"], [0], [self._mint(conn, i)], ["x"],
                    csn0=i, ref=head,
                )
                conn.submit_frame(f)
        svc.pump()
        floor = None
        for p in range(svc.log.n_partitions):
            doc = svc._deli._lambdas[p]._docs.get("doc")
            if doc:
                floor = doc.sequencer.min_seq
        assert floor and floor > 2
        # Stale frame: old ref, correct next csn -> nack, csn unconsumed.
        f = OpFrame.build("s", ["ins"], [0], [self._mint(a, 7)], ["y"],
                          csn0=7, ref=1)
        a.submit_frame(f)
        assert a.nacks and a.nacks[0].client_sequence_number == 7
        a.nacks.clear()
        # Resubmission: SAME csn, fresh ref (the recovery shape). If deli
        # read csns as refs this would nack forever (csn 7 < MSN).
        f = OpFrame.build("s", ["ins"], [0], [self._mint(a, 7)], ["y"],
                          csn0=7, ref=svc.doc_head("doc"))
        a.submit_frame(f)
        svc.pump()
        assert not a.nacks

    def test_connect_while_frames_in_flight(self):
        """A join racing live frame traffic must not crash connect():
        raw SeqFrames can land in the connecting inbox ahead of the
        sequenced join (code-review r5)."""
        svc = PipelineFluidService(n_partitions=1)
        a = svc.connect("doc")
        from fluidframework_tpu.service.lambdas import RAW_TOPIC

        f = OpFrame.build("s", ["ins", "ins"], [0, 1],
                          [self._mint(a, 1), self._mint(a, 2)], ["x", "y"],
                          csn0=1, ref=svc.doc_head("doc"))
        # Enqueue WITHOUT pumping: the frame sequences during connect()'s
        # own pump, after the new conn has joined the room.
        svc.log.send(RAW_TOPIC, "doc",
                     {"t": "opframe", "client": a.client_id, "frame": f})
        b = svc.connect("doc")
        assert b.client_id >= 0
        # The raced frame is still delivered to B, expanded on read.
        texts = [m.contents["contents"].get("text")
                 for m in b.take_inbox()
                 if getattr(m, "type", None) == MessageType.OPERATION]
        assert "x" in texts and "y" in texts

    def test_frame_nack_reaches_connection(self):
        svc = PipelineFluidService(n_partitions=1)
        conn = svc.connect("doc")
        f = OpFrame.build("s", ["ins"], [0], [self._mint(conn, 1)], ["x"],
                          csn0=5, ref=svc.doc_head("doc"))  # gap: expected 1
        conn.submit_frame(f)
        svc.pump()
        assert conn.nacks and conn.nacks[0].content_code == 400
        assert conn.nacks[0].client_sequence_number == 5


class TestFrameContention:
    """Frame-wire contention (r6 satellite): >=8 writers on ONE document
    driving concurrent frames through the full pipeline, interleaved with
    replay duplicates and a stale-ref batch — convergence asserted, and
    every sequenced stamp (seq/csn/ref/msn/client) plus the dup-drop and
    nack behavior must match the per-op JSON path exactly."""

    N_WRITERS = 8

    def _frame(self, conn, k, csn0, ref, orig0):
        texts = [chr(97 + (orig0 + i) % 26) for i in range(k)]
        return OpFrame.build(
            "s", ["ins"] * k, [0] * k,
            [conn.conn_no * MINT + orig0 + i for i in range(k)],
            texts, csn0=csn0, ref=ref,
        ), texts

    def _ops(self, conn, k, csn0, ref, orig0):
        texts = [chr(97 + (orig0 + i) % 26) for i in range(k)]
        return [
            DocumentMessage(
                client_sequence_number=csn0 + i,
                reference_sequence_number=ref,
                type=MessageType.OPERATION,
                contents={"address": "s", "contents": {
                    "k": "ins", "pos": 0, "text": texts[i],
                    "orig": conn.conn_no * MINT + orig0 + i,
                }},
            )
            for i in range(k)
        ]

    def test_eight_writer_contention_matches_per_op_path(self):
        rng = np.random.default_rng(17)
        svc_f = PipelineFluidService(n_partitions=1)
        svc_j = PipelineFluidService(n_partitions=1)
        NW = self.N_WRITERS
        wf = [svc_f.connect("doc") for _ in range(NW)]
        wj = [svc_j.connect("doc") for _ in range(NW)]
        for a, b in zip(wf, wj):
            assert (a.client_id, a.conn_no) == (b.client_id, b.conn_no)
        csn = [0] * NW
        orig = [0] * NW
        k = 3
        last = [None] * NW  # (csn0, ref, orig0) of the last sent batch
        for rnd in range(4):
            # One shared ref per round = genuine concurrency: every
            # writer authors against the round-start head, so deli's MSN
            # floor moves under interleaving, not in lockstep.
            ref = svc_f.doc_head("doc")
            assert ref == svc_j.doc_head("doc")
            for w in rng.permutation(NW):
                f, _ = self._frame(wf[w], k, csn[w] + 1, ref, orig[w])
                wf[w].submit_frame(f)
                for m in self._ops(wj[w], k, csn[w] + 1, ref, orig[w]):
                    wj[w].submit(m)
                last[w] = (csn[w] + 1, ref, orig[w])
                csn[w] += k
                orig[w] += k
            # Replay duplicate: one writer resends its previous batch
            # whole — silent drop on both wires (checkOrder).
            w = int(rng.integers(0, NW))
            c0, r0, o0 = last[w]
            dup, _ = self._frame(wf[w], k, c0, r0, o0)
            wf[w].submit_frame(dup)
            for m in self._ops(wj[w], k, c0, r0, o0):
                wj[w].submit(m)
            assert not wf[w].nacks and not wj[w].nacks

        # Stale-ref batch: ref 0 sits below the MSN by now. The frame
        # nacks once at its first op; per-op ticketing nacks the first op
        # the same way (later ops die on the csn gap — same net effect:
        # nothing sequences, same first nack, csn not consumed).
        assert svc_f.doc_head("doc") == svc_j.doc_head("doc")
        f, _ = self._frame(wf[0], k, csn[0] + 1, 0, orig[0])
        wf[0].submit_frame(f)
        for m in self._ops(wj[0], k, csn[0] + 1, 0, orig[0]):
            wj[0].submit(m)
        assert wf[0].nacks and wj[0].nacks
        nf, nj = wf[0].nacks[0], wj[0].nacks[0]
        assert (nf.content_code, nf.client_sequence_number) == (
            nj.content_code, nj.client_sequence_number) == (400, csn[0] + 1)
        # Recovery: SAME csn0, fresh ref — sequences on both wires.
        ref = svc_f.doc_head("doc")
        f, _ = self._frame(wf[0], k, csn[0] + 1, ref, orig[0])
        wf[0].submit_frame(f)
        for m in self._ops(wj[0], k, csn[0] + 1, ref, orig[0]):
            wj[0].submit(m)
        csn[0] += k
        orig[0] += k

        # Every sequenced stamp matches the per-op path, op for op.
        ops_f = [m for m in svc_f.get_deltas("doc")
                 if m.type == MessageType.OPERATION]
        ops_j = [m for m in svc_j.get_deltas("doc")
                 if m.type == MessageType.OPERATION]
        assert len(ops_f) == len(ops_j) == (4 * NW + 1) * k
        for a, b in zip(ops_f, ops_j):
            assert (
                a.sequence_number, a.client_id, a.client_sequence_number,
                a.reference_sequence_number, a.minimum_sequence_number,
                a.contents,
            ) == (
                b.sequence_number, b.client_id, b.client_sequence_number,
                b.reference_sequence_number, b.minimum_sequence_number,
                b.contents,
            )
        # And the device replicas converge to the same document.
        assert svc_f.device_text("doc", "s") == svc_j.device_text("doc", "s")
        assert svc_f.device.stats()["docs_with_errors"] == 0
