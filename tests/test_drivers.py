"""Driver layer: local factory/url-resolution, file capture, replay.

Reference: packages/drivers/* — local-driver, file-driver, replay-driver
(SURVEY.md §2.3). The replay flow is BASELINE.json config 1's harness:
capture a session, then play the op log into a fresh read-only container
and land on the identical state, stoppable at any intermediate seq.
"""

import pytest

from fluidframework_tpu.drivers import (
    LocalDocumentServiceFactory,
    load_document,
    resolve_url,
    save_document,
)
from fluidframework_tpu.models.shared_map import SharedMap
from fluidframework_tpu.models.shared_string import SharedString
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.local_server import LocalFluidService


def drain(rts):
    for rt in rts:
        rt.flush()
    while any(rt.process_incoming() for rt in rts):
        pass


def record_session(svc, doc="doc"):
    a = ContainerRuntime(svc, doc, channels=(SharedString("s"), SharedMap("m")))
    b = ContainerRuntime(svc, doc, channels=(SharedString("s"), SharedMap("m")))
    a.get_channel("s").insert_text(0, "hello ")
    b.get_channel("m").set("k", 1)
    drain([a, b])
    b.get_channel("s").insert_text(6, "world")
    a.get_channel("m").set("k", 2)
    drain([a, b])
    a.get_channel("s").remove_range(0, 3)
    drain([a, b])
    return a, b


class TestLocalDriver:
    def test_url_resolution(self):
        assert resolve_url("fluid-test://host/doc-1") == "doc-1"
        assert resolve_url("fluid-test://host/abc/path/x") == "abc"
        with pytest.raises(AssertionError):
            resolve_url("https://elsewhere/doc")

    def test_factory_binds_documents(self):
        factory = LocalDocumentServiceFactory()
        ds = factory.create_document_service("fluid-test://host/d1")
        conn = ds.connect()
        assert conn.client_id == 0
        ds2 = factory.create_document_service("fluid-test://host/d1")
        assert ds2.connect().client_id == 1  # same doc, same sequencer
        assert factory.create_document_service(
            "fluid-test://host/other"
        ).connect().client_id == 0


class TestFileAndReplay:
    def test_capture_replay_full(self, tmp_path):
        svc = LocalFluidService()
        a, b = record_session(svc)
        save_document(svc, "doc", str(tmp_path / "cap"))

        fds = load_document(str(tmp_path / "cap"), doc_id="doc")
        replay = fds.as_replay_service()
        rt = ContainerRuntime(
            replay, "doc", channels=(SharedString("s"), SharedMap("m")), mode="read"
        )
        assert rt.get_channel("s").get_text() == a.get_channel("s").get_text()
        assert rt.get_channel("m").get("k") == a.get_channel("m").get("k")

    def test_stepped_replay_intermediate_states(self, tmp_path):
        svc = LocalFluidService()
        a, b = record_session(svc)
        save_document(svc, "doc", str(tmp_path / "cap"))

        fds = load_document(str(tmp_path / "cap"), doc_id="doc")
        replay = fds.as_replay_service(replay_to=0)
        rt = ContainerRuntime(
            replay, "doc", channels=(SharedString("s"), SharedMap("m")), mode="read"
        )
        assert rt.get_channel("s").get_text() == ""
        states = []
        head = max(m.sequence_number for m in fds.ops)
        for seq in range(1, head + 1):
            replay.replay_to(seq)
            rt.process_incoming()
            states.append(rt.get_channel("s").get_text())
        assert states[-1] == a.get_channel("s").get_text()
        # The text passed through its intermediate value before the remove.
        assert "hello world" in states
        assert rt.ref_seq == head

    def test_replay_from_summary_snapshot(self, tmp_path):
        svc = LocalFluidService()
        a, b = record_session(svc)
        a.submit_summary()
        drain([a, b])
        # More edits after the summary: replay must load snapshot + tail.
        a.get_channel("s").insert_text(0, ">>")
        drain([a, b])
        save_document(svc, "doc", str(tmp_path / "cap"))

        fds = load_document(str(tmp_path / "cap"), doc_id="doc")
        assert fds.initial_summary is not None
        rt = ContainerRuntime(
            fds.as_replay_service(), "doc",
            channels=(SharedString("s"), SharedMap("m")), mode="read",
        )
        assert rt.get_channel("s").get_text() == a.get_channel("s").get_text()
        assert rt.last_summary_seq == fds.initial_summary[1]

    def test_replay_is_readonly(self, tmp_path):
        svc = LocalFluidService()
        record_session(svc)
        save_document(svc, "doc", str(tmp_path / "cap"))
        fds = load_document(str(tmp_path / "cap"), doc_id="doc")
        rt = ContainerRuntime(
            fds.as_replay_service(), "doc",
            channels=(SharedString("s"), SharedMap("m")), mode="read",
        )
        head = rt.ref_seq
        # Local edits go nowhere: the stream never advances.
        rt.get_channel("m").set("x", 1)
        rt.flush()
        rt.process_incoming()
        assert rt.ref_seq == head
