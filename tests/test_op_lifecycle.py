"""Op virtualization: compression, chunking, batch atomicity.

Covers the reference's opLifecycle machinery (opCompressor.ts,
opSplitter.ts, remoteMessageProcessor.ts, scheduleManager.ts — D.1 in
SURVEY.md): batches over the threshold compress into message[0] plus
empty placeholders; oversized single ops split into chunks reassembled
before processing; inbound batches are never split mid-way.
"""

import pytest

from fluidframework_tpu.models.shared_map import SharedMap
from fluidframework_tpu.models.shared_string import SharedString
from fluidframework_tpu.protocol.types import MessageType
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.runtime.op_lifecycle import (
    RemoteMessageProcessor,
    pack_batch,
)
from fluidframework_tpu.service.local_server import LocalFluidService


def make_pair(doc="doc", **kw):
    svc = LocalFluidService()
    a = ContainerRuntime(svc, doc, channels=(SharedString("s"), SharedMap("m")), **kw)
    b = ContainerRuntime(svc, doc, channels=(SharedString("s"), SharedMap("m")), **kw)
    return svc, a, b


def sync(*containers):
    for c in containers:
        c.process_incoming()
    for c in containers:
        c.process_incoming()


class TestPackBatch:
    def test_small_batch_passes_through(self):
        wire = pack_batch([{"address": "m", "contents": {"k": 1}}])
        assert len(wire) == 1
        assert wire[0].contents == {"address": "m", "contents": {"k": 1}}
        assert wire[0].logical_index == 0

    def test_compression_reserves_one_seq_per_op(self):
        envs = [{"address": "m", "contents": {"k": "x" * 100}} for _ in range(8)]
        wire = pack_batch(envs, compression_threshold=64)
        assert len(wire) == 8
        assert "packedContents" in wire[0].contents
        assert all(w.contents is None for w in wire[1:])
        assert [w.logical_index for w in wire] == list(range(8))
        assert wire[0].metadata.get("batchBegin")
        assert wire[-1].metadata.get("batchEnd")

    def test_chunking_only_final_chunk_acks(self):
        envs = [{"address": "s", "contents": {"text": "y" * 500}}]
        wire = pack_batch(envs, compression_threshold=None, chunk_size=128)
        assert len(wire) > 2
        assert all("chunkedOp" in w.contents for w in wire)
        assert [w.logical_index for w in wire[:-1]] == [None] * (len(wire) - 1)
        assert wire[-1].logical_index == 0

    def test_roundtrip_through_processor(self):
        envs = [{"address": "m", "contents": {"k": i, "pad": "z" * 200}} for i in range(5)]
        for kw in (
            dict(compression_threshold=64),
            dict(compression_threshold=None, chunk_size=100),
            dict(compression_threshold=None, chunk_size=None),
        ):
            rmp = RemoteMessageProcessor()
            out = []
            seq = 0
            for w in pack_batch(envs, **kw):
                seq += 1
                from fluidframework_tpu.protocol.types import (
                    SequencedDocumentMessage,
                )

                got = rmp.process(
                    SequencedDocumentMessage(
                        client_id=0,
                        sequence_number=seq,
                        client_sequence_number=seq,
                        reference_sequence_number=0,
                        minimum_sequence_number=0,
                        type=MessageType.OPERATION,
                        contents=w.contents,
                        metadata=w.metadata,
                    )
                )
                if got is not None:
                    out.append(got.contents)
            assert out == envs


class TestEndToEnd:
    def test_compressed_batch_converges(self):
        svc, a, b = make_pair(compression_threshold=128, chunk_size=None)
        s = a.get_channel("s")
        for i in range(10):
            s.insert_text(0, f"block{i:03d}x" * 4)
        a.flush()
        sync(a, b)
        assert b.get_channel("s").get_text() == s.get_text()
        assert len(s.get_text()) == 10 * 36
        # The wire carried a compressed first message + placeholders.
        ops = [
            d
            for d in svc.get_deltas("doc")
            if d.type == MessageType.OPERATION and d.client_id == a.client_id
        ]
        assert len(ops) == 10  # one seq number per logical op

    def test_chunked_large_op_converges(self):
        svc, a, b = make_pair(compression_threshold=None, chunk_size=256)
        s = a.get_channel("s")
        s.insert_text(0, "A" * 2000)
        a.flush()
        sync(a, b)
        assert b.get_channel("s").get_text() == "A" * 2000
        # More wire messages than logical ops (the chunks).
        ops = [d for d in svc.get_deltas("doc") if d.type == MessageType.OPERATION]
        assert len(ops) > 1

    def test_local_echo_with_compression(self):
        svc, a, b = make_pair(compression_threshold=1, chunk_size=None)
        m = a.get_channel("m")
        for i in range(6):
            m.set(f"k{i}", i)
        a.flush()
        sync(a, b)
        assert not a.pending
        assert b.get_channel("m").get("k5") == 5
        assert a.get_channel("m").get("k0") == 0

    def test_interleaved_compressed_batches_two_clients(self):
        svc, a, b = make_pair(compression_threshold=1, chunk_size=None)
        am, bm = a.get_channel("m"), b.get_channel("m")
        for i in range(4):
            am.set(f"a{i}", i)
            bm.set(f"b{i}", i)
        a.flush()
        b.flush()
        sync(a, b)
        assert am.keys() == bm.keys()
        assert len(am.keys()) == 8

    def test_batch_atomicity_never_splits(self):
        svc, a, b = make_pair(compression_threshold=None, chunk_size=None)
        m = a.get_channel("m")
        for i in range(5):
            m.set(f"k{i}", i)
        a.flush()
        # Ask b for just one message: the whole 5-op batch must land (the
        # reference pauses the inbound queue only at batch boundaries).
        b.process_incoming(1)
        keys = b.get_channel("m").keys()
        assert len(keys) == 5

    def test_chunking_survives_reconnect_resubmit(self):
        svc, a, b = make_pair(compression_threshold=None, chunk_size=64)
        s = a.get_channel("s")
        a.disconnect()
        s.insert_text(0, "offline-edit " * 50)
        a.reconnect()
        sync(a, b)
        assert b.get_channel("s").get_text() == s.get_text()
        assert len(s.get_text()) == 13 * 50


class TestReviewRegressions:
    def test_empty_batch_always_compress(self):
        assert pack_batch([], compression_threshold=0) == []
        svc = LocalFluidService()
        rt = ContainerRuntime(
            svc, "doc", channels=(SharedMap("m"),), compression_threshold=0
        )
        rt.get_channel("m").set("k", 1)
        rt.flush()
        rt.process_incoming()
        assert rt.get_channel("m").get("k") == 1

    def test_compressed_head_is_chunked_when_oversized(self):
        envs = [{"address": "m", "contents": {"k": i, "pad": "w" * 400}} for i in range(20)]
        wire = pack_batch(envs, compression_threshold=64, chunk_size=128)
        # Head compressed then chunked; placeholders follow; every wire
        # payload respects the chunk size.
        assert all(
            len(w.contents.get("chunkedOp", {}).get("data", "")) <= 128
            for w in wire
            if isinstance(w.contents, dict) and "chunkedOp" in w.contents
        )
        assert sum(1 for w in wire if w.contents is None) == 19
        rmp = RemoteMessageProcessor()
        from fluidframework_tpu.protocol.types import SequencedDocumentMessage

        out = []
        for seq, w in enumerate(wire, 1):
            got = rmp.process(
                SequencedDocumentMessage(
                    client_id=0, sequence_number=seq, client_sequence_number=seq,
                    reference_sequence_number=0, minimum_sequence_number=0,
                    type=MessageType.OPERATION, contents=w.contents, metadata=w.metadata,
                )
            )
            if got is not None:
                out.append(got.contents)
        assert out == envs

    def test_compressed_chunked_end_to_end(self):
        svc, a, b = make_pair(compression_threshold=64, chunk_size=100)
        m = a.get_channel("m")
        for i in range(10):
            m.set(f"key{i}", "v" * 50)
        a.flush()
        sync(a, b)
        assert b.get_channel("m").keys() == m.keys()
        assert len(m.keys()) == 10
