"""Changeset algebra law checks — the verifyChangeRebaser analog
(reference ``tree/src/core/rebase/verifyChangeRebaser.ts``)."""

import numpy as np
import pytest

from fluidframework_tpu.tree import marks as M


def random_state(rng, n=None):
    n = int(rng.integers(0, 9)) if n is None else n
    return [int(x) for x in rng.integers(100, 999, n)]


def random_change(rng, state):
    """A valid changeset over `state` (mix of skips, deletes, inserts)."""
    out = []
    i = 0
    while i < len(state):
        r = rng.random()
        run = int(rng.integers(1, 4))
        run = min(run, len(state) - i)
        if r < 0.4:
            out.append(M.skip(run))
            i += run
        elif r < 0.7:
            out.append(M.delete(state[i : i + run]))
            i += run
        else:
            out.append(M.insert(random_state(rng, int(rng.integers(1, 3)))))
    if rng.random() < 0.5:
        out.append(M.insert(random_state(rng, int(rng.integers(1, 3)))))
    return M.normalize(out)


def test_apply_basics():
    s = [1, 2, 3, 4]
    c = [M.skip(1), M.delete([2, 3]), M.insert([9])]
    assert M.apply(s, c) == [1, 9, 4]


def test_invert_roundtrip_directed():
    s = [1, 2, 3]
    c = [M.skip(1), M.delete([2]), M.insert([7, 8])]
    out = M.apply(s, c)
    assert M.apply(out, M.invert(c)) == s


@pytest.mark.parametrize("seed", range(30))
def test_invert_roundtrip_fuzz(seed):
    rng = np.random.default_rng(seed)
    s = random_state(rng)
    c = random_change(rng, s)
    out = M.apply(s, c)
    assert M.apply(out, M.invert(c)) == s
    # Double inversion is identity up to normalization.
    assert M.normalize(M.invert(M.invert(c))) == M.normalize(c)


@pytest.mark.parametrize("seed", range(30))
def test_compose_matches_sequential_apply(seed):
    rng = np.random.default_rng(seed + 1000)
    s = random_state(rng)
    a = random_change(rng, s)
    mid = M.apply(s, a)
    b = random_change(rng, mid)
    assert M.apply(s, M.compose(a, b)) == M.apply(mid, b)


@pytest.mark.parametrize("seed", range(30))
def test_compose_associative(seed):
    rng = np.random.default_rng(seed + 2000)
    s = random_state(rng)
    a = random_change(rng, s)
    s1 = M.apply(s, a)
    b = random_change(rng, s1)
    s2 = M.apply(s1, b)
    c = random_change(rng, s2)
    left = M.compose(M.compose(a, b), c)
    right = M.compose(a, M.compose(b, c))
    assert M.apply(s, left) == M.apply(s, right)


def test_compose_identity():
    rng = np.random.default_rng(7)
    s = random_state(rng)
    c = random_change(rng, s)
    assert M.apply(s, M.compose([], c)) == M.apply(s, c)
    assert M.apply(s, M.compose(c, [])) == M.apply(s, c)


@pytest.mark.parametrize("seed", range(40))
def test_rebase_convergence_pairwise(seed):
    """The core two-client law: applying a then rebase(b, a) equals
    applying b then rebase(a, b) with the mirrored tie policy."""
    rng = np.random.default_rng(seed + 3000)
    s = random_state(rng)
    a = random_change(rng, s)
    b = random_change(rng, s)
    via_a = M.apply(M.apply(s, a), M.rebase(b, a))
    via_b = M.apply(M.apply(s, b), M.rebase(a, b, c_after=True))
    assert via_a == via_b


@pytest.mark.parametrize("seed", range(20))
def test_rebase_over_inverse_returns(seed):
    """rebase(rebase(c, o), invert(o)) ≍ c when o deletes nothing that c
    touches (the reference's axiom, restricted like verifyChangeRebaser's
    tolerance for content lost under deletion)."""
    rng = np.random.default_rng(seed + 4000)
    s = random_state(rng)
    # o: insert-only change (no information loss).
    o = M.normalize(
        [M.skip(int(rng.integers(0, len(s) + 1))), M.insert(random_state(rng, 2))]
    )
    c = random_change(rng, s)
    back = M.rebase(M.rebase(c, o), M.invert(o))
    assert M.apply(s, back) == M.apply(s, c)


def test_rebase_insert_tie_later_lands_left():
    s = [1, 2]
    a = [M.skip(1), M.insert([10])]  # earlier-sequenced
    b = [M.skip(1), M.insert([20])]  # later-sequenced
    merged = M.apply(M.apply(s, a), M.rebase(b, a))
    assert merged == [1, 20, 10, 2]


def test_rebase_insert_inside_deleted_range_slides():
    s = [1, 2, 3, 4]
    o = [M.skip(1), M.delete([2, 3])]  # deletes the middle
    c = [M.skip(2), M.insert([9])]  # insert between 2 and 3
    out = M.apply(M.apply(s, o), M.rebase(c, o))
    assert out == [1, 9, 4]


# ---------------------------------------------------------------------------
# Moves (mout/min — the reference sequence-field MoveOut/MoveIn,
# format.ts:14-220; capture/splice semantics per moveEffectTable.ts).


def random_change_with_moves(rng, state):
    """A valid changeset over `state` mixing all five mark kinds."""
    out = []
    i = 0
    mid = 0
    pending = []  # (mid, count) move-ins yet to be placed
    while i < len(state):
        r = rng.random()
        run = int(rng.integers(1, 4))
        run = min(run, len(state) - i)
        if pending and rng.random() < 0.35:
            m, n = pending.pop()
            out.append(M.move_in(m, n))
            continue
        if r < 0.3:
            out.append(M.skip(run))
            i += run
        elif r < 0.55:
            out.append(M.delete(state[i : i + run]))
            i += run
        elif r < 0.75:
            out.append(M.insert(random_state(rng, int(rng.integers(1, 3)))))
        else:
            out.append(M.move_out(mid, state[i : i + run]))
            pending.append((mid, run))
            mid += 1
            i += run
    for m, n in pending:
        out.append(M.move_in(m, n))
    if rng.random() < 0.5:
        out.append(M.insert(random_state(rng, int(rng.integers(1, 3)))))
    return M.normalize(out)


def test_move_apply_and_invert_directed():
    s = [1, 2, 3, 4, 5]
    c = [M.skip(1), M.move_out(0, [2, 3]), M.skip(2), M.move_in(0, 2)]
    assert M.apply(s, c) == [1, 4, 5, 2, 3]
    assert M.apply(M.apply(s, c), M.invert(c)) == s
    # Move left: the attach precedes the detach in mark order.
    c2 = [M.move_in(7, 2), M.skip(3), M.move_out(7, [4, 5])]
    assert M.apply(s, c2) == [4, 5, 1, 2, 3]
    assert M.apply(M.apply(s, c2), M.invert(c2)) == s


def test_compose_delete_of_moved_content_dies_at_source():
    s = [1, 2, 3, 4, 5]
    move = [M.skip(1), M.move_out(0, [2, 3]), M.skip(2), M.move_in(0, 2)]
    kill = [M.skip(3), M.delete([2, 3])]
    assert M.apply(s, M.compose(move, kill)) == [1, 4, 5]


def test_compose_chained_moves():
    s = [1, 2, 3, 4, 5]
    move = [M.skip(1), M.move_out(0, [2, 3]), M.skip(2), M.move_in(0, 2)]
    again = [M.move_in(1, 2), M.skip(3), M.move_out(1, [2, 3])]
    assert M.apply(s, M.compose(move, again)) == [2, 3, 1, 4, 5]


def test_rebase_marks_follow_moved_content():
    """c deletes content that over moved: the delete follows the content
    to its destination (moveEffectTable semantics)."""
    s = [1, 2, 3, 4, 5]
    over = [M.skip(1), M.move_out(0, [2, 3]), M.skip(2), M.move_in(0, 2)]
    c = [M.skip(1), M.delete([2, 3])]
    assert M.apply(M.apply(s, over), M.rebase(c, over)) == [1, 4, 5]


def test_rebase_both_move_later_wins():
    """Both sides move the same unit: the later-sequenced move wins in
    either application order."""
    s = [1, 2, 3]
    a = [M.move_in(0, 1), M.skip(2), M.move_out(0, [3])]  # 3 to front
    b = [M.skip(2), M.move_out(0, [3]), M.move_in(0, 1)]  # 3 stays-ish
    via_a = M.apply(M.apply(s, a), M.rebase(b, a))
    via_b = M.apply(M.apply(s, b), M.rebase(a, b, c_after=True))
    assert via_a == via_b


def test_attach_stays_at_source_when_region_moves():
    """An insert positioned inside a region that over moved anchors at
    the source boundary (attaches do not follow moves)."""
    s = [1, 2, 3, 4]
    over = [M.skip(1), M.move_out(0, [2, 3]), M.skip(1), M.move_in(0, 2)]
    c = [M.skip(2), M.insert([9])]  # between 2 and 3
    out = M.apply(M.apply(s, over), M.rebase(c, over))
    assert out == [1, 9, 4, 2, 3]


def test_lower_moves_preserves_apply():
    rng = np.random.default_rng(11)
    for seed in range(20):
        rng = np.random.default_rng(seed + 7000)
        s = random_state(rng)
        c = random_change_with_moves(rng, s)
        lowered = M.lower_moves(c)
        assert not M.has_moves(lowered)
        assert M.apply(s, lowered) == M.apply(s, c)


@pytest.mark.parametrize("seed", range(60))
def test_move_laws_fuzz(seed):
    """All four algebra laws over move-bearing changesets."""
    rng = np.random.default_rng(seed + 12000)
    s = random_state(rng)
    a = random_change_with_moves(rng, s)
    out = M.apply(s, a)
    # invert round trip
    assert M.apply(out, M.invert(a)) == s
    # compose == sequential apply
    b = random_change_with_moves(rng, out)
    assert M.apply(s, M.compose(a, b)) == M.apply(out, b)
    # associativity
    s2 = M.apply(out, b)
    c = random_change_with_moves(rng, s2)
    left = M.compose(M.compose(a, b), c)
    right = M.compose(a, M.compose(b, c))
    assert M.apply(s, left) == M.apply(s, right)
    # pairwise rebase convergence
    b2 = random_change_with_moves(rng, s)
    via_a = M.apply(M.apply(s, a), M.rebase(b2, a))
    via_b = M.apply(M.apply(s, b2), M.rebase(a, b2, c_after=True))
    assert via_a == via_b


@pytest.mark.parametrize("seed", range(20))
def test_dense_lower_lift_roundtrip(seed):
    """from_marks (dense lowering) followed by lift_dense reproduces the
    normalized changeset exactly — mout/min included (the r7 dense move
    lanes are a lossless encoding of the mark IR, up to run merging)."""
    from fluidframework_tpu.ops import tree_kernel as TK

    rng = np.random.default_rng(seed + 21000)
    s = random_state(rng)
    c = random_change_with_moves(rng, s)
    dc, L = TK.from_marks(c, 64, 64)
    lifted = M.lift_dense(
        dc.del_mask, dc.ins_cnt, dc.ins_ids, dc.mov_id, dc.mov_off,
        dc.pool_mid, dc.pool_off, len(s), s,
    )
    assert M.apply(s, lifted) == M.apply(s, c)
    assert M.normalize(lifted) == M.normalize(c)


@pytest.mark.parametrize("seed", range(30))
def test_unit_engine_matches_run_engine_move_free(seed):
    """The unit-level canonical engine (the move path) must agree with
    the run-based co-iteration on move-free inputs — each implementation
    checks the other."""
    rng = np.random.default_rng(seed + 13000)
    s = random_state(rng)
    a = random_change(rng, s)
    o = M.apply(s, a)
    b = random_change(rng, o)
    assert M.apply(s, M._compose_units(a, b)) == M.apply(
        s, M._compose_runs(a, b)
    )
    c = random_change(rng, s)
    for c_after in (False, True):
        assert M.apply(
            M.apply(s, a), M._rebase_units(c, a, c_after)
        ) == M.apply(M.apply(s, a), M._rebase_runs(c, a, c_after))
