"""Changeset algebra law checks — the verifyChangeRebaser analog
(reference ``tree/src/core/rebase/verifyChangeRebaser.ts``)."""

import numpy as np
import pytest

from fluidframework_tpu.tree import marks as M


def random_state(rng, n=None):
    n = int(rng.integers(0, 9)) if n is None else n
    return [int(x) for x in rng.integers(100, 999, n)]


def random_change(rng, state):
    """A valid changeset over `state` (mix of skips, deletes, inserts)."""
    out = []
    i = 0
    while i < len(state):
        r = rng.random()
        run = int(rng.integers(1, 4))
        run = min(run, len(state) - i)
        if r < 0.4:
            out.append(M.skip(run))
            i += run
        elif r < 0.7:
            out.append(M.delete(state[i : i + run]))
            i += run
        else:
            out.append(M.insert(random_state(rng, int(rng.integers(1, 3)))))
    if rng.random() < 0.5:
        out.append(M.insert(random_state(rng, int(rng.integers(1, 3)))))
    return M.normalize(out)


def test_apply_basics():
    s = [1, 2, 3, 4]
    c = [M.skip(1), M.delete([2, 3]), M.insert([9])]
    assert M.apply(s, c) == [1, 9, 4]


def test_invert_roundtrip_directed():
    s = [1, 2, 3]
    c = [M.skip(1), M.delete([2]), M.insert([7, 8])]
    out = M.apply(s, c)
    assert M.apply(out, M.invert(c)) == s


@pytest.mark.parametrize("seed", range(30))
def test_invert_roundtrip_fuzz(seed):
    rng = np.random.default_rng(seed)
    s = random_state(rng)
    c = random_change(rng, s)
    out = M.apply(s, c)
    assert M.apply(out, M.invert(c)) == s
    # Double inversion is identity up to normalization.
    assert M.normalize(M.invert(M.invert(c))) == M.normalize(c)


@pytest.mark.parametrize("seed", range(30))
def test_compose_matches_sequential_apply(seed):
    rng = np.random.default_rng(seed + 1000)
    s = random_state(rng)
    a = random_change(rng, s)
    mid = M.apply(s, a)
    b = random_change(rng, mid)
    assert M.apply(s, M.compose(a, b)) == M.apply(mid, b)


@pytest.mark.parametrize("seed", range(30))
def test_compose_associative(seed):
    rng = np.random.default_rng(seed + 2000)
    s = random_state(rng)
    a = random_change(rng, s)
    s1 = M.apply(s, a)
    b = random_change(rng, s1)
    s2 = M.apply(s1, b)
    c = random_change(rng, s2)
    left = M.compose(M.compose(a, b), c)
    right = M.compose(a, M.compose(b, c))
    assert M.apply(s, left) == M.apply(s, right)


def test_compose_identity():
    rng = np.random.default_rng(7)
    s = random_state(rng)
    c = random_change(rng, s)
    assert M.apply(s, M.compose([], c)) == M.apply(s, c)
    assert M.apply(s, M.compose(c, [])) == M.apply(s, c)


@pytest.mark.parametrize("seed", range(40))
def test_rebase_convergence_pairwise(seed):
    """The core two-client law: applying a then rebase(b, a) equals
    applying b then rebase(a, b) with the mirrored tie policy."""
    rng = np.random.default_rng(seed + 3000)
    s = random_state(rng)
    a = random_change(rng, s)
    b = random_change(rng, s)
    via_a = M.apply(M.apply(s, a), M.rebase(b, a))
    via_b = M.apply(M.apply(s, b), M.rebase(a, b, c_after=True))
    assert via_a == via_b


@pytest.mark.parametrize("seed", range(20))
def test_rebase_over_inverse_returns(seed):
    """rebase(rebase(c, o), invert(o)) ≍ c when o deletes nothing that c
    touches (the reference's axiom, restricted like verifyChangeRebaser's
    tolerance for content lost under deletion)."""
    rng = np.random.default_rng(seed + 4000)
    s = random_state(rng)
    # o: insert-only change (no information loss).
    o = M.normalize(
        [M.skip(int(rng.integers(0, len(s) + 1))), M.insert(random_state(rng, 2))]
    )
    c = random_change(rng, s)
    back = M.rebase(M.rebase(c, o), M.invert(o))
    assert M.apply(s, back) == M.apply(s, c)


def test_rebase_insert_tie_later_lands_left():
    s = [1, 2]
    a = [M.skip(1), M.insert([10])]  # earlier-sequenced
    b = [M.skip(1), M.insert([20])]  # later-sequenced
    merged = M.apply(M.apply(s, a), M.rebase(b, a))
    assert merged == [1, 20, 10, 2]


def test_rebase_insert_inside_deleted_range_slides():
    s = [1, 2, 3, 4]
    o = [M.skip(1), M.delete([2, 3])]  # deletes the middle
    c = [M.skip(2), M.insert([9])]  # insert between 2 and 3
    out = M.apply(M.apply(s, o), M.rebase(c, o))
    assert out == [1, 9, 4]
