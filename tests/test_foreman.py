"""Foreman: service-side task assignment on the sequenced stream.

Reference: lambdas/src/foreman/lambda.ts:20 — the service farms tasks out
to connected clients and re-farms on disconnect (VERDICT r2 Missing #6)."""

from fluidframework_tpu.models.shared_map import SharedMap
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.pipeline import PipelineFluidService


def drain(rts):
    for rt in rts:
        rt.flush()
    while any(rt.process_incoming() for rt in rts):
        pass


def foreman_signals(conn):
    return [
        s.content for s in conn.signals
        if isinstance(s.content, dict) and "foreman" in s.content
    ]


def test_first_writer_gets_the_task():
    svc = PipelineFluidService(n_partitions=2)
    a = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
    b = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
    svc.pump()
    got_a = foreman_signals(a.connection)
    assert got_a, "assignment signal must reach the room"
    assert got_a[-1] == {"foreman": "summarizer", "assignee": a.client_id}
    # The second join does not steal the task.
    got_b = foreman_signals(b.connection)
    assert all(s["assignee"] == a.client_id for s in got_b)


def test_task_migrates_on_disconnect():
    """The e2e contract: the service-assigned task moves to a surviving
    client when its holder disconnects, and the new assignee can act on
    it (here: produce the summary the task exists for)."""
    svc = PipelineFluidService(n_partitions=2)
    a = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
    b = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
    a.get_channel("m").set("k", 1)
    drain([a, b])
    assert foreman_signals(a.connection)[-1]["assignee"] == a.client_id
    a.disconnect()
    svc.pump()
    sigs = foreman_signals(b.connection)
    assert sigs and sigs[-1]["assignee"] == b.client_id, sigs
    # The new assignee performs the task it was handed.
    b.submit_summary()
    drain([b])
    assert b.last_summary_seq > 0


def test_read_only_clients_are_not_assigned():
    svc = PipelineFluidService(n_partitions=2)
    ro_conn = svc.connect("doc", mode="read")
    svc.pump()
    assert not foreman_signals(ro_conn), "read clients must not be farmed"
    w = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
    svc.pump()
    sigs = foreman_signals(w.connection)
    assert sigs and sigs[-1]["assignee"] == w.client_id


def test_replayed_foreman_never_duplicates_signals():
    """At-least-once hardening: a foreman restarted from a STALE (or
    absent) checkpoint replays joins and re-emits its assignment signals —
    deli's per-group monotone basis floor must drop every re-emission, so
    clients see each assignment exactly once."""
    from fluidframework_tpu.service.foreman import ForemanDocLambda
    from fluidframework_tpu.service.lambdas import (
        DELTAS_TOPIC,
        CheckpointStore,
        DocumentLambda,
        PartitionRunner,
    )

    svc = PipelineFluidService(n_partitions=2)  # lazy checkpoints
    a = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
    b = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
    svc.pump()
    before = foreman_signals(a.connection)
    assert before
    # Crash with NO checkpoint: the replacement replays the full topic.
    def factory(p, state):
        lam = DocumentLambda(lambda d, s: ForemanDocLambda(d, s))
        lam.restore_docs(state)
        return lam

    svc._foreman = PartitionRunner(
        svc.log, DELTAS_TOPIC, "foreman", factory, CheckpointStore(), 10
    )
    svc.pump()
    assert foreman_signals(a.connection) == before, (
        "replayed assignment signals must be deduped by the basis floor"
    )
    # And the floor is not a wall: a REAL membership change still signals.
    a.disconnect()
    svc.pump()
    assert foreman_signals(b.connection)[-1]["assignee"] == b.client_id


def test_assignment_survives_foreman_restart():
    """Checkpoint + replay: a restarted foreman re-derives the same
    assignment deterministically (no flapping, no duplicate signals)."""
    svc = PipelineFluidService(n_partitions=2, checkpoint_every=1)
    a = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
    b = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
    svc.pump()
    before = foreman_signals(a.connection)
    # Restart the foreman runner from its checkpoint (crash_deli analog).
    from fluidframework_tpu.service.foreman import ForemanDocLambda
    from fluidframework_tpu.service.lambdas import (
        DELTAS_TOPIC,
        DocumentLambda,
        PartitionRunner,
    )

    def factory(p, state):
        lam = DocumentLambda(lambda d, s: ForemanDocLambda(d, s))
        lam.restore_docs(state)
        return lam

    svc._foreman = PartitionRunner(
        svc.log, DELTAS_TOPIC, "foreman", factory, svc.checkpoints, 1
    )
    a.get_channel("m").set("k", 2)
    drain([a, b])
    after = foreman_signals(a.connection)
    assert after == before  # no re-assignment churn after the restart
    a.disconnect()
    svc.pump()
    assert foreman_signals(b.connection)[-1]["assignee"] == b.client_id
