"""Framework layer: fluid-static schema containers, client facade, aqueduct.

Mirrors the reference's fluid-static/azure-client/aqueduct test shapes:
schema round-trips through create/load, dynamic objects live and die by
handle reachability, data-object lifecycle hooks fire on the right clients.
"""

from fluidframework_tpu.drivers.local_driver import LocalDocumentServiceFactory
from fluidframework_tpu.framework.client import TpuClientProps, TpuFluidClient
from fluidframework_tpu.framework.data_object import (
    ContainerRuntimeFactoryWithDefaultDataStore,
    DataObject,
    DataObjectFactory,
)
from fluidframework_tpu.framework.fluid_static import ContainerSchema
from fluidframework_tpu.models.shared_cell import SharedCell
from fluidframework_tpu.models.shared_counter import SharedCounter
from fluidframework_tpu.models.shared_map import SharedMap
from fluidframework_tpu.models.shared_string import SharedString

SCHEMA = ContainerSchema(
    initial_objects={
        "map": SharedMap,
        "text": SharedString,
        "count": SharedCounter,
    },
    dynamic_object_types=(SharedCell,),
)


def make_client():
    return TpuFluidClient(TpuClientProps(LocalDocumentServiceFactory()))


def pump(*containers):
    for c in containers:
        c.runtime.flush()
    for c in containers:
        c.runtime.process_incoming()


def test_create_container_initial_objects():
    client = make_client()
    container, doc_id = client.create_container(SCHEMA)
    objs = container.initial_objects
    assert set(objs) == {"map", "text", "count"}
    objs["map"].set("k", 1)
    objs["text"].insert_text(0, "hi")
    pump(container)
    assert objs["map"].get("k") == 1
    assert objs["text"].get_text() == "hi"


def test_two_clients_collaborate_via_schema():
    client = make_client()
    c1, doc_id = client.create_container(SCHEMA)
    c1.initial_objects["map"].set("who", "c1")
    c1.initial_objects["text"].insert_text(0, "hello")
    pump(c1)

    c2 = client.get_container(doc_id, SCHEMA)
    assert c2.initial_objects["map"].get("who") == "c1"
    assert c2.initial_objects["text"].get_text() == "hello"
    c2.initial_objects["text"].insert_text(5, " world")
    pump(c2, c1)
    assert c1.initial_objects["text"].get_text() == "hello world"
    assert set(c1.audience) == set(c2.audience)
    assert len(c1.audience) == 2


def test_dynamic_object_create_and_handle_roundtrip():
    client = make_client()
    c1, doc_id = client.create_container(SCHEMA)
    cell = c1.create(SharedCell)
    cell.set("payload")
    c1.initial_objects["map"].set("cell", c1.handle_of(cell))
    pump(c1)
    resolved = c1.resolve_handle(c1.initial_objects["map"].get("cell"))
    assert resolved is cell
    # Referenced by a rooted map -> survives GC.
    result = c1.runtime.run_gc()
    assert f"/{cell.id}" not in result.unreferenced


def test_dynamic_object_unreferenced_is_gc_candidate():
    client = make_client()
    c1, _ = client.create_container(SCHEMA)
    cell = c1.create(SharedCell)
    cell.set("orphan")
    pump(c1)
    result = c1.runtime.run_gc()
    assert f"/{cell.id}" in result.unreferenced


def test_schema_mismatch_create_rejected():
    import pytest

    client = make_client()
    c1, _ = client.create_container(SCHEMA)
    with pytest.raises(AssertionError):
        c1.create(SharedMap)  # not in dynamic_object_types


def test_unknown_container_id_rejected():
    import pytest

    client = make_client()
    with pytest.raises(AssertionError):
        client.get_container("no-such-doc", SCHEMA)


def test_dynamic_object_replicates_to_other_clients():
    client = make_client()
    c1, doc_id = client.create_container(SCHEMA)
    cell = c1.create(SharedCell)
    cell.set("shared-payload")
    c1.initial_objects["map"].set("cell", c1.handle_of(cell))
    pump(c1)

    # A client that loads later replays the ATTACH op and realizes the cell.
    c2 = client.get_container(doc_id, SCHEMA)
    remote_cell = c2.resolve_handle(c2.initial_objects["map"].get("cell"))
    assert remote_cell.get() == "shared-payload"
    remote_cell.set("updated")
    pump(c2, c1)
    assert cell.get() == "updated"


def test_dynamic_object_created_while_disconnected_replicates():
    client = make_client()
    c1, doc_id = client.create_container(SCHEMA)
    pump(c1)
    c1.disconnect()
    cell = c1.create(SharedCell)  # ATTACH buffered, not submitted
    cell.set("offline-made")
    c1.initial_objects["map"].set("cell", c1.handle_of(cell))
    c1.runtime.flush()
    c1.connect()  # resends the attach, then the offline ops
    pump(c1)

    c2 = client.get_container(doc_id, SCHEMA)
    remote = c2.resolve_handle(c2.initial_objects["map"].get("cell"))
    assert remote.get() == "offline-made"


def test_dynamic_object_survives_summary_load():
    client = make_client()
    c1, doc_id = client.create_container(SCHEMA)
    cell = c1.create(SharedCell)
    cell.set("persisted")
    c1.initial_objects["map"].set("cell", c1.handle_of(cell))
    pump(c1)
    c1.runtime.submit_summary()
    pump(c1)

    # Summary-loaded client reconstructs the dynamic channel from its
    # recorded type, without replaying the ATTACH op.
    c3 = client.get_container(doc_id, SCHEMA)
    # Catch-up started at the summary seq (the ATTACH op is below it and was
    # not replayed), then advanced over the ack + c3's own join.
    assert c3.runtime.ref_seq >= c1.runtime.last_summary_seq > 0
    cell3 = c3.resolve_handle(c3.initial_objects["map"].get("cell"))
    assert cell3.get() == "persisted"


class Counter(DataObject):
    """Tiny aqueduct-style data object."""

    def initializing_first_time(self, props=None) -> None:
        self.root.set("value", props or 0)

    def initializing_from_existing(self) -> None:
        assert self.root.has("value")

    def increment(self) -> None:
        self.root.set("value", self.value + 1)

    @property
    def value(self) -> int:
        return self.root.get("value")


def test_data_object_lifecycle_and_collab():
    from fluidframework_tpu.service.local_server import LocalFluidService

    service = LocalFluidService()
    factory = ContainerRuntimeFactoryWithDefaultDataStore(
        DataObjectFactory("counter", Counter)
    )
    rt1, obj1 = factory.instantiate(service, "doc-a", existing=False, props=10)
    assert obj1.value == 10
    obj1.increment()
    rt1.flush()
    rt1.process_incoming()

    rt2, obj2 = factory.instantiate(service, "doc-a", existing=True)
    assert obj2.value == 11
    obj2.increment()
    rt2.flush()
    rt2.process_incoming()
    rt1.process_incoming()
    assert obj1.value == 12 and obj2.value == 12


def test_dynamic_data_object_via_registry():
    from fluidframework_tpu.service.local_server import LocalFluidService

    service = LocalFluidService()
    factory = ContainerRuntimeFactoryWithDefaultDataStore(
        DataObjectFactory("counter", Counter)
    )
    rt1, obj1 = factory.instantiate(service, "doc-b", existing=False, props=0)
    extra = factory.create_data_object(rt1, "counter", "extra", props=100)
    extra.increment()
    rt1.flush()
    rt1.process_incoming()

    rt2, _ = factory.instantiate(service, "doc-b", existing=True)
    remote = factory.get_data_object(rt2, "extra")
    assert remote.value == 101
