"""The capacity cliff under the PRODUCTION default (VERDICT r3 do #7).

The reference never hits a cliff — its merge-tree B-tree grows by root
splits (``mergeTree.ts:1268``) and zamboni scours keep blocks bounded
(``zamboni.ts:19-60``). Fixed kernel shapes make unbounded in-place
growth impossible here, so the divergence is BOUNDED by policy and both
policies are pinned at the pipeline level:

- ``sharded_overflow=False`` (the default): a document that outgrows the
  top fleet tier gets 429 LIMIT_EXCEEDED nacks on further writes, but
  STAYS READABLE — device reads serve the last applied state and client
  replicas are unaffected. Default rationale: promotion re-homes ONE
  document onto a ShardedDoc spanning the whole device mesh — a
  deliberate capacity allocation an operator must size (the same reason
  the reference caps message sizes at 16KB rather than growing forever,
  ``config.json:55``) — so the conservative default refuses instead of
  silently claiming the mesh.
- ``sharded_overflow=True``: the document re-homes into a ShardedDoc
  mid-session; clients see no nacks and collaboration continues across
  the promotion.
"""

from fluidframework_tpu.models.shared_string import SharedString
from fluidframework_tpu.protocol.types import NackErrorType
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.pipeline import PipelineFluidService


def drain(rts):
    for rt in rts:
        rt.flush()
    while any(rt.process_incoming() for rt in rts):
        pass


def _grow(runtime, n, start=0):
    s = runtime.get_channel("s")
    for i in range(start, start + n):
        s.insert_text(0, chr(ord("a") + i % 26))
        if i % 4 == 3:
            drain([runtime])
    drain([runtime])


def test_default_cliff_nacks_but_document_stays_readable():
    svc = PipelineFluidService(
        n_partitions=2, device_capacity=8, device_max_capacity=8
    )
    assert svc.device.sharded_overflow is False  # the production default
    a = ContainerRuntime(svc, "doc", channels=(SharedString("s"),))
    nacks = []
    a.connection.on_nack = nacks.append
    _grow(a, 6)
    svc.flush_device()
    readable_before = svc.device_text("doc", "s")
    assert len(readable_before) == 6  # served from device pre-cliff
    _grow(a, 8, start=6)  # now > 8 rows: over the top tier
    svc.flush_device()
    assert any(
        n.error_type == NackErrorType.LIMIT_EXCEEDED
        and n.content_code == 429
        for n in nacks
    ), "the cliff must surface as 429 on the write path"
    # Contract: the document DID NOT die —
    # 1. device reads still serve (last applied state, no crash);
    text = svc.device_text("doc", "s")
    assert isinstance(text, str) and len(text) >= 6
    # 2. the client replica is intact and still collaborating host-side;
    assert len(a.get_channel("s").get_text()) == 14
    b = ContainerRuntime(svc, "doc", channels=(SharedString("s"),))
    drain([a, b])
    assert b.get_channel("s").get_text() == a.get_channel("s").get_text()
    # 3. telemetry names the document.
    assert svc.device.stats()["docs_with_errors"] == 1


def test_overflow_promotion_keeps_clients_unaffected():
    svc = PipelineFluidService(
        n_partitions=2, device_capacity=8, device_max_capacity=8,
        device_sharded_overflow=True,
    )
    a = ContainerRuntime(svc, "doc", channels=(SharedString("s"),))
    nacks = []
    a.connection.on_nack = nacks.append
    _grow(a, 14)  # crosses the top tier mid-session
    svc.flush_device()
    assert not nacks, "promotion must absorb the growth without nacks"
    stats = svc.device.stats()
    assert stats["sharded_docs"] == 1  # re-homed onto the mesh
    assert stats["docs_with_errors"] == 0
    # The device keeps serving the FULL document from sharded state...
    assert len(svc.device_text("doc", "s")) == 14
    # ...and collaboration continues across the promotion.
    b = ContainerRuntime(svc, "doc", channels=(SharedString("s"),))
    b.get_channel("s").insert_text(0, "Z")
    drain([a, b])
    assert a.get_channel("s").get_text() == b.get_channel("s").get_text()
    svc.flush_device()
    assert svc.device_text("doc", "s") == a.get_channel("s").get_text()
