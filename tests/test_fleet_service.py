"""TpuFleetService — the fleet-scale serving path (native ticketing +
fused Pallas apply + device-scribe summaries) as a product module.

Reference: deli partition ownership (``deli/lambda.ts:742``) + scribe
summary production (``scribe/lambda.ts:106,304``); VERDICT r2 items 1/6."""

import numpy as np

from fluidframework_tpu.ops import encode as E
from fluidframework_tpu.protocol.constants import OP_WIDTH
from fluidframework_tpu.service.fleet_service import TpuFleetService


def _round(svc, per_doc_rows):
    """Build (intents, rows) for one boxcar: per_doc_rows[d] = list of
    unstamped kernel rows for doc d (same count per doc)."""
    k = len(per_doc_rows[0])
    n = svc.n_docs
    rows = np.zeros((n, k, OP_WIDTH), np.int32)
    intents = np.zeros((n, k, 3), np.int32)
    start = svc.fseq.doc_state[:, 0].astype(np.int64)
    cseq0 = svc.fseq.clients[:, 0, 1].astype(np.int64)
    for d in range(n):
        for i, r in enumerate(per_doc_rows[d]):
            rows[d, i] = r
            intents[d, i] = (0, cseq0[d] + 1 + i, start[d] + i)
    return intents, rows


def make_service(n_docs=8, capacity=64):
    svc = TpuFleetService(
        n_docs, capacity=capacity, block_docs=n_docs, interpret=True
    )
    svc.join_writer(0)
    return svc


def test_fleet_service_applies_and_serves_text():
    svc = make_service()
    pay = {1: "hello", 2: " world"}
    per_doc = [
        [E.insert(0, 1, 5), E.insert(5, 2, 6)] for _ in range(svc.n_docs)
    ]
    intents, rows = _round(svc, per_doc)
    err, _ = svc.submit_round(intents, rows)
    assert not err.any()
    assert not svc.device_errors().any()
    for d in range(svc.n_docs):
        assert svc.text(d, pay) == "hello world"


def test_fleet_service_remove_and_steady_state():
    svc = make_service()
    pay = {1: "abcdef"}
    r1 = [[E.insert(0, 1, 6)] for _ in range(svc.n_docs)]
    err, _ = svc.submit_round(*_round(svc, r1))
    assert not err.any()
    r2 = [[E.remove(1, 3)] for _ in range(svc.n_docs)]
    err, _ = svc.submit_round(*_round(svc, r2))
    assert not err.any()
    for d in range(svc.n_docs):
        assert svc.text(d, pay) == "adef"


def test_fleet_service_ticket_error_nacks_doc_without_applying():
    svc = make_service()
    pay = {1: "xx"}
    intents, rows = _round(svc, [[E.insert(0, 1, 2)]] * svc.n_docs)
    intents[3, 0, 1] = 99  # cseq gap on doc 3: native loop must refuse
    err, _ = svc.submit_round(intents, rows)
    assert err[3] != 0 and not err[[d for d in range(8) if d != 3]].any()
    assert svc.text(3, pay) == ""  # refused round applied nothing
    assert svc.text(0, pay) == "xx"


def test_device_scribe_summarizes_only_dirty_docs():
    svc = make_service()
    pay = {1: "summary"}
    err, _ = svc.submit_round(*_round(svc, [[E.insert(0, 1, 7)]] * svc.n_docs))
    assert not err.any()
    n, total = svc.summarize_dirty(threshold=1)
    assert n == svc.n_docs and total > 0
    # Clean fleet: nothing advanced, nothing summarized.
    n2, _ = svc.summarize_dirty(threshold=1)
    assert n2 == 0
    # The blob round-trips into the client channel-summary lane format.
    summary = svc.latest_summary(0)
    summary["payloads"] = pay
    from fluidframework_tpu.models.shared_string import SharedString

    class _Rt:
        client_id = 0
        conn_no = 0

        def register_dirty(self, *_a, **_k):
            pass

    fresh = SharedString("s")
    fresh._runtime = _Rt()
    fresh.attach(_Rt())
    fresh.load_core(summary)
    assert fresh.get_text() == "summary"


def test_device_scribe_threshold_gates_writes():
    svc = make_service()
    err, _ = svc.submit_round(*_round(svc, [[E.insert(0, 1, 1)]] * svc.n_docs))
    assert not err.any()
    n, _ = svc.summarize_dirty(threshold=5)  # each doc advanced only 1 seq
    assert n == 0


def test_submit_round_returns_stamped_rows_without_mutating_input():
    svc = make_service()
    intents, rows = _round(svc, [[E.insert(0, 1, 2)]] * svc.n_docs)
    before = rows.copy()
    err, stamped = svc.submit_round(intents, rows)
    assert not err.any()
    assert (rows == before).all()  # caller's buffer untouched
    from fluidframework_tpu.protocol.constants import F_SEQ

    assert (stamped[:, 0, F_SEQ] > 0).all()  # sequenced form returned
