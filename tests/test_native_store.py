"""Native C++ content-addressed store tests (builds via make on demand)."""

import hashlib
import os
import tempfile

import pytest

from fluidframework_tpu.utils.native import (
    NativeBlobStore,
    native_store_available,
)

pytestmark = pytest.mark.skipif(
    not native_store_available(), reason="native toolchain unavailable"
)


def test_roundtrip_and_digest_parity():
    s = NativeBlobStore()
    data = b"hello native world" * 100
    h = s.put_blob(data)
    # The C++ SHA-256 must agree with Python's (handles are interchangeable
    # between the native and dict backends).
    assert h == hashlib.sha256(data).hexdigest()
    assert s.has(h)
    assert s.get_blob(h) == data
    assert not s.has("0" * 64)


def test_empty_and_binary_blobs():
    s = NativeBlobStore()
    h0 = s.put_blob(b"")
    assert h0 == hashlib.sha256(b"").hexdigest()
    assert s.get_blob(h0) == b""
    blob = bytes(range(256)) * 33
    h = s.put_blob(blob)
    assert s.get_blob(h) == blob


def test_disk_persistence():
    with tempfile.TemporaryDirectory() as d:
        s = NativeBlobStore(d)
        h = s.put_blob(b"durable")
        del s
        s2 = NativeBlobStore(d)
        assert s2.has(h)
        assert s2.get_blob(h) == b"durable"
        assert os.path.exists(os.path.join(d, h[:2], h[2:]))


def test_summary_store_over_native_backend():
    from fluidframework_tpu.service.summary_store import SummaryStore

    store = SummaryStore(native=True)
    summary = {
        "sequence_number": 7,
        "quorum": [0, 1],
        "channels": {"text": {"lanes": {"kind": [1]}, "count": 1}},
    }
    h = store.put_summary(summary)
    out = store.get_summary(h)
    assert out["sequence_number"] == 7
    assert out["channels"]["text"]["count"] == 1


def test_e2e_service_on_native_store():
    from fluidframework_tpu.models.shared_string import SharedString
    from fluidframework_tpu.runtime.container import ContainerRuntime
    from fluidframework_tpu.service.local_server import LocalFluidService
    from fluidframework_tpu.service.summary_store import SummaryStore

    svc = LocalFluidService(store=SummaryStore(native=True))
    a = ContainerRuntime(svc, "doc", channels=(SharedString("text"),))
    a.get_channel("text").insert_text(0, "native-backed summary")
    a.flush()
    a.process_incoming()
    a.submit_summary()
    a.process_incoming()
    b = ContainerRuntime(svc, "doc", channels=(SharedString("text"),))
    assert b.get_channel("text").get_text() == "native-backed summary"
