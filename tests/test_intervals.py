"""Interval collections + local references (reference intervalCollection.ts,
localReference.ts; SURVEY.md A.9): position stability under concurrent
edits, slide-on-remove, multi-client convergence, reconnect rebase, and
summary round-trip."""

import numpy as np
import pytest

from fluidframework_tpu.models.interval_collection import DETACHED
from fluidframework_tpu.models.shared_string import SharedString
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.local_server import LocalFluidService


def make_pair(n=2):
    svc = LocalFluidService()
    rts = [
        ContainerRuntime(svc, "doc", channels=(SharedString("text"),))
        for _ in range(n)
    ]
    return svc, rts, [rt.get_channel("text") for rt in rts]


def drain(rts):
    for rt in rts:
        rt.flush()
    while any(rt.process_incoming() for rt in rts):
        pass


def test_reference_shifts_with_inserts_and_removes():
    svc, (a,), (sa,) = (lambda s, r, c: (s, r, c))(*make_pair(1))
    sa.insert_text(0, "hello world")
    drain([a])
    ref = sa.create_local_reference(6)  # the 'w'
    assert sa.ref_position(ref) == 6

    sa.insert_text(0, ">> ")
    drain([a])
    assert sa.ref_position(ref) == 9

    sa.remove_range(0, 3)
    drain([a])
    assert sa.ref_position(ref) == 6


def test_reference_slides_forward_on_acked_remove():
    svc, (a,), (sa,) = (lambda s, r, c: (s, r, c))(*make_pair(1))
    sa.insert_text(0, "abcdef")
    drain([a])
    ref = sa.create_local_reference(2)  # 'c'
    sa.remove_range(1, 4)  # removes bcd; ref should slide fwd to 'e'
    drain([a])
    assert sa.get_text() == "aef"
    assert sa.ref_position(ref) == 1  # 'e'


def test_reference_slides_backward_at_document_end():
    svc, (a,), (sa,) = (lambda s, r, c: (s, r, c))(*make_pair(1))
    sa.insert_text(0, "abc")
    drain([a])
    ref = sa.create_local_reference(2, bias="bwd")
    sa.remove_range(1, 3)
    drain([a])
    assert sa.get_text() == "a"
    assert sa.ref_position(ref) == 0


def test_reference_detaches_when_document_emptied():
    svc, (a,), (sa,) = (lambda s, r, c: (s, r, c))(*make_pair(1))
    sa.insert_text(0, "xyz")
    drain([a])
    ref = sa.create_local_reference(1)
    sa.remove_range(0, 3)
    drain([a])
    assert sa.ref_position(ref) == DETACHED


def test_interval_add_and_resolve_two_clients():
    svc, rts, (sa, sb) = make_pair()
    sa.insert_text(0, "the quick brown fox")
    drain(rts)

    ca = sa.get_interval_collection("comments")
    iid = ca.add(4, 8, props={"author": "a"})  # "quick"
    drain(rts)

    cb = sb.get_interval_collection("comments")
    assert cb.resolve(iid) == (4, 8)
    assert cb.get(iid).props == {"author": "a"}

    # Remote insert before the interval shifts it on both replicas.
    sb.insert_text(0, ">>> ")
    drain(rts)
    assert ca.resolve(iid) == (8, 12)
    assert cb.resolve(iid) == (8, 12)


def test_interval_endpoints_resolved_at_sender_perspective():
    svc, rts, (sa, sb) = make_pair()
    sa.insert_text(0, "abcdefgh")
    drain(rts)

    # B adds an interval over "cde" while A concurrently prepends text the
    # sender has not seen; the interval must still cover "cde" everywhere.
    cb = sb.get_interval_collection("x")
    iid = cb.add(2, 4)
    sa.insert_text(0, "123")
    drain(rts)

    assert sa.get_text() == sb.get_text() == "123abcdefgh"
    assert sa.get_interval_collection("x").resolve(iid) == (5, 7)
    assert cb.resolve(iid) == (5, 7)


def test_interval_slides_on_concurrent_remove():
    svc, rts, (sa, sb) = make_pair()
    sa.insert_text(0, "abcdefgh")
    drain(rts)

    ca = sa.get_interval_collection("x")
    iid = ca.add(2, 5)  # "cdef"
    drain(rts)

    sb.remove_range(1, 4)  # removes bcd: start anchor 'c' gone
    drain(rts)

    assert sa.get_text() == "aefgh"
    ra = sa.get_interval_collection("x").resolve(iid)
    rb = sb.get_interval_collection("x").resolve(iid)
    assert ra == rb == (1, 2)  # slid fwd to 'e', end still 'f'


def test_interval_change_lww_and_local_pending_wins():
    svc, rts, (sa, sb) = make_pair()
    sa.insert_text(0, "abcdefgh")
    drain(rts)
    ca = sa.get_interval_collection("x")
    cb = sb.get_interval_collection("x")
    iid = ca.add(0, 1)
    drain(rts)

    # Concurrent changes: A moves to (2,3), B moves to (5,6). Both flush;
    # the later-sequenced change wins on every replica.
    ca.change(iid, start=2, end=3)
    cb.change(iid, start=5, end=6)
    rts[0].flush()
    rts[1].flush()
    drain(rts)
    assert ca.resolve(iid) == cb.resolve(iid)


def test_interval_delete_wins_everywhere():
    svc, rts, (sa, sb) = make_pair()
    sa.insert_text(0, "abcdefgh")
    drain(rts)
    ca = sa.get_interval_collection("x")
    cb = sb.get_interval_collection("x")
    iid = ca.add(0, 3)
    drain(rts)

    cb.delete(iid)
    ca.change(iid, start=1, end=2)  # concurrent change loses to delete
    drain(rts)
    assert ca.get(iid) is None
    assert cb.get(iid) is None


def test_interval_reconnect_resubmits_pending_add():
    svc, rts, (sa, sb) = make_pair()
    sa.insert_text(0, "abcdefgh")
    drain(rts)

    rts[0].disconnect()
    ca = sa.get_interval_collection("x")
    iid = ca.add(2, 4)
    # B edits while A is offline.
    sb.insert_text(0, "ZZ")
    rts[1].flush()
    drain([rts[1]])

    rts[0].reconnect()
    drain(rts)
    assert sa.get_text() == sb.get_text() == "ZZabcdefgh"
    assert sa.get_interval_collection("x").resolve(iid) == (4, 6)
    assert sb.get_interval_collection("x").resolve(iid) == (4, 6)


def test_interval_summary_round_trip():
    svc, rts, (sa, sb) = make_pair()
    sa.insert_text(0, "hello world")
    ca = sa.get_interval_collection("marks")
    iid = ca.add(6, 10, props={"tag": "w"})
    drain(rts)

    summary = sa.summarize_core()
    fresh = SharedString("text")

    class _FakeRuntime:
        client_id = 7

        def submit_channel_op(self, *a, **k):  # pragma: no cover
            raise AssertionError("no ops during load")

    fresh.attach(_FakeRuntime())
    fresh.load_core(summary)
    assert fresh.get_text() == "hello world"
    col = fresh.get_interval_collection("marks")
    assert col.resolve(iid) == (6, 10)
    assert col.get(iid).props == {"tag": "w"}


def test_concurrent_disjoint_field_changes_merge():
    """A pending local start move shields only start: concurrent end/props
    changes from another client still land (per-field overlay, reference
    pendingChange tracking in intervalCollection.ts)."""
    svc, (a, b), (sa, sb) = (lambda s, r, c: (s, r, c))(*make_pair(2))
    sa.insert_text(0, "0123456789")
    drain([a, b])
    col_a = sa.get_interval_collection("c")
    col_b = sb.get_interval_collection("c")
    iid = col_a.add(0, 1)
    drain([a, b])

    # Concurrent: a moves start, b moves end and sets a prop.
    col_a.change(iid, start=3)
    col_b.change(iid, end=6, props={"bold": 1})
    drain([a, b])

    assert col_a.resolve(iid) == col_b.resolve(iid) == (3, 6)
    assert col_a.get(iid).props == col_b.get(iid).props == {"bold": 1}


def test_concurrent_same_field_latest_seq_wins():
    svc, (a, b), (sa, sb) = (lambda s, r, c: (s, r, c))(*make_pair(2))
    sa.insert_text(0, "0123456789")
    drain([a, b])
    col_a = sa.get_interval_collection("c")
    col_b = sb.get_interval_collection("c")
    iid = col_a.add(0, 9)
    drain([a, b])

    col_a.change(iid, start=2)
    col_b.change(iid, start=5)
    drain([a, b])
    # Both replicas agree; the later-sequenced change holds the field.
    assert col_a.resolve(iid) == col_b.resolve(iid)
    assert col_a.resolve(iid)[0] in (2, 5)


def test_interval_searches():
    """findOverlappingIntervals / nextInterval / previousInterval."""
    from fluidframework_tpu.models.shared_string import SharedString
    from fluidframework_tpu.runtime.container import ContainerRuntime
    from fluidframework_tpu.service.local_server import LocalFluidService

    svc = LocalFluidService()
    a = ContainerRuntime(svc, "d", channels=(SharedString("t"),))
    s = a.get_channel("t")
    s.insert_text(0, "abcdefghij")
    a.flush()
    a.process_incoming()
    col = s.get_interval_collection("marks")
    i1 = col.add(1, 3)
    i2 = col.add(4, 6)
    i3 = col.add(8, 9)
    a.flush()
    a.process_incoming()

    assert set(col.find_overlapping(2, 5)) == {i1, i2}
    assert col.find_overlapping(7, 7) == []
    assert col.next_interval(4) == i2
    assert col.next_interval(7) == i3
    assert col.next_interval(50) is None
    assert col.previous_interval(3) == i1
    assert col.previous_interval(0) is None
    # Searches track sliding positions through edits.
    s.remove_range(0, 2)  # i1 start slides
    a.flush()
    a.process_incoming()
    assert col.previous_interval(0) == i1


def test_interval_anchor_sees_hi_lane_removers():
    """A remover in writer slot >= 31 (second removers lane) must hide the
    removed rows from its own perspective in interval anchoring, exactly as
    the kernel's visibility does (regression: two-lane mask widening)."""
    import numpy as np

    from fluidframework_tpu.models.interval_collection import anchor_from_pos
    from fluidframework_tpu.ops import encode as E
    from fluidframework_tpu.ops.merge_kernel import jit_apply_ops
    from fluidframework_tpu.ops.segment_state import make_state, to_host
    from fluidframework_tpu.protocol.constants import NO_CLIENT

    rows = [
        E.insert(0, 1, 6, seq=1, ref=0, client=40),  # "abcdef"
        E.remove(1, 3, seq=2, ref=1, client=33),  # hi-lane remover
    ]
    st = jit_apply_ops(make_state(32, NO_CLIENT), np.stack(rows).astype(np.int32))
    h = to_host(st)
    # From remover 33's perspective the text is "adef": position 1 anchors
    # to the character 'd' (orig 1, offset 3).
    anchor = anchor_from_pos(h, 1, ref_seq=2, client=33)
    assert anchor == (1, 3), anchor
