"""graftlint (tools/graftlint): fixture-snippet unit tests per pass — at
least one true positive and one true negative each — plus the wire-drift
lock behavior (a mutated opframe codec must trip the fingerprint check)
and the repo-wide CI invariant (`--check` exits 0 with an empty
baseline)."""

import ast
import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tools():
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.graftlint import core  # noqa: F401
    from tools.graftlint.passes import (
        DeterminismPass,
        HostSyncPass,
        RecompileHazardPass,
        wire_drift,
    )

    return core, HostSyncPass, RecompileHazardPass, DeterminismPass, wire_drift


def _run_pass(pass_cls, snippet, tmp_path, relpath="fluidframework_tpu/x.py"):
    """Run one pass over a fixture snippet; returns surviving findings
    (pragma suppression applied, baseline not)."""
    core = _tools()[0]
    abspath = tmp_path / "snippet.py"
    abspath.write_text(textwrap.dedent(snippet))
    src = core.ModuleSource.load(str(tmp_path), "snippet.py")
    src.path = relpath  # scopes are resolved by the runner, not the pass
    p = pass_cls()
    return [
        f for f, node in p.run(src) if not src.suppressed(f, node)
    ]


# -- host-sync -----------------------------------------------------------------


def test_host_sync_flags_asarray_on_device_attr(tmp_path):
    _, HostSync, *_ = _tools()
    findings = _run_pass(
        HostSync,
        """
        import numpy as np

        def stats(pool):
            return np.asarray(pool.state.err)
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "device→host" in findings[0].message


def test_host_sync_flags_scalarize_of_jitted_result(tmp_path):
    _, HostSync, *_ = _tools()
    findings = _run_pass(
        HostSync,
        """
        import jax
        import numpy as np

        @jax.jit
        def _scan(s):
            return s.sum()

        def probe(pool):
            dev = _scan(pool.state)
            return int(dev)
        """,
        tmp_path,
    )
    assert [f.message.split("(")[0] for f in findings] == ["int"]


def test_host_sync_true_negatives(tmp_path):
    _, HostSync, *_ = _tools()
    findings = _run_pass(
        HostSync,
        """
        import numpy as np

        def host_only(rows):
            # host numpy staging is NOT a readback
            buf = np.asarray(rows, np.int64)
            n = int(buf.max())
            # .shape metadata is host-resident even on device arrays
            def shapes(pool):
                return int(pool.state.shape[0])
            # np.asarray result is host: downstream int() is clean
            host = np.asarray(pool_state_like(), np.int32)
            return n, int(host[0])

        def pool_state_like():
            return [1, 2, 3]
        """,
        tmp_path,
    )
    assert findings == []


def test_host_sync_pragma_suppresses_with_reason(tmp_path):
    _, HostSync, *_ = _tools()
    findings = _run_pass(
        HostSync,
        """
        import numpy as np

        def stats(pool):
            return np.asarray(pool.state.err)  # graftlint: readback(explicit stats barrier)
        """,
        tmp_path,
    )
    assert findings == []


def test_host_sync_pragma_without_reason_does_not_suppress(tmp_path):
    core, HostSync, *_ = _tools()
    abspath = tmp_path / "snippet.py"
    abspath.write_text(
        "import numpy as np\n"
        "def stats(pool):\n"
        "    return np.asarray(pool.state.err)  # graftlint: readback\n"
    )
    src = core.ModuleSource.load(str(tmp_path), "snippet.py")
    survivors = [
        f for f, node in HostSync().run(src) if not src.suppressed(f, node)
    ]
    assert len(survivors) == 1  # reasonless pragma suppresses nothing
    pragma_errors = core.pragma_findings(src)
    assert len(pragma_errors) == 1
    assert "no reason" in pragma_errors[0].message


def test_host_sync_telemetry_slice_readback_pragma(tmp_path):
    """The r9 telemetry-lane shape: a jitted per-shard reduction whose
    single batched result is read back once per /metrics scrape. The
    np.asarray IS a device→host transfer — flagged bare, suppressed by
    the reasoned one-readback-per-scrape pragma."""
    _, HostSync, *_ = _tools()
    snippet = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    @jax.jit
    def _pool_telemetry(state, n_shards):
        return state.count.reshape(n_shards, -1).sum(axis=1)

    def telemetry_slice(pool, n_shards):
        dev = _pool_telemetry(pool.state, n_shards)
        return np.asarray(dev){pragma}
    """
    bare = _run_pass(HostSync, snippet.format(pragma=""), tmp_path)
    assert len(bare) == 1 and "device→host" in bare[0].message
    annotated = _run_pass(
        HostSync,
        snippet.format(
            pragma="  # graftlint: readback(the ONE batched telemetry"
            " readback per /metrics scrape)"
        ),
        tmp_path,
    )
    assert annotated == []


def test_host_sync_pump_scan_consume_readback_pragma(tmp_path):
    """The r10 pump's ONLY legal readback: consuming the one-boxcar-
    stale health scan. The np.asarray over the jitted scan result IS a
    device→host transfer — flagged bare, suppressed by the reasoned
    one-readback-per-round pragma the production pump carries."""
    _, HostSync, *_ = _tools()
    snippet = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    @jax.jit
    def _pool_scan(state):
        return jnp.stack([state.count, state.err])

    def pump_round(pool, staged_rows):
        dev = _pool_scan(pool.state)  # begin_scan: async, no transfer
        host = np.asarray(dev){pragma}
        return host
    """
    bare = _run_pass(HostSync, snippet.format(pragma=""), tmp_path)
    assert len(bare) == 1 and "device→host" in bare[0].message
    annotated = _run_pass(
        HostSync,
        snippet.format(
            pragma="  # graftlint: readback(the pump's one-boxcar-stale"
            " health scan — the only device→host transfer per round)"
        ),
        tmp_path,
    )
    assert annotated == []


def test_host_sync_ticker_scan_prefetch_readback_pragma(tmp_path):
    """The r12 deadline ticker's off-loop prefetch shape: the blocking
    half of the scan consume (np.array over the token's device arrays)
    moved off the event loop. It is the SAME one-boxcar-stale transfer
    the pump would run inline — the ticker performs ZERO new readbacks —
    so the np.array is flagged bare and suppressed only by the reasoned
    pragma the production ``scan_transfer`` carries."""
    _, HostSync, *_ = _tools()
    snippet = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    @jax.jit
    def _pool_scan(state):
        return jnp.stack([state.count, state.err])

    def tick_prefetch(pool):
        # the deadline ticker's off-loop half: transfer the in-flight
        # scan token's device snapshot (run_in_executor), so the on-loop
        # feed consumes it without blocking
        dev = _pool_scan(pool.state)  # the token's async snapshot
        return np.array(dev){pragma}
    """
    bare = _run_pass(HostSync, snippet.format(pragma=""), tmp_path)
    assert len(bare) == 1 and "device→host" in bare[0].message
    annotated = _run_pass(
        HostSync,
        snippet.format(
            pragma="  # graftlint: readback(the pump's one-boxcar-stale"
            " health scan, run off-loop by the deadline ticker — the"
            " same single transfer per round, zero new readbacks)"
        ),
        tmp_path,
    )
    assert annotated == []


# -- recompile-hazard ----------------------------------------------------------


def test_recompile_flags_jit_in_loop(tmp_path):
    _, _, Recompile, *_ = _tools()
    findings = _run_pass(
        Recompile,
        """
        import jax

        for blk in (8, 16):
            step = jax.jit(lambda s: s)
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "inside a loop" in findings[0].message


def test_recompile_flags_per_call_construction(tmp_path):
    _, _, Recompile, *_ = _tools()
    findings = _run_pass(
        Recompile,
        """
        import jax

        def make_step(mesh):
            return jax.jit(lambda s: s)
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "per call" in findings[0].message


def test_recompile_allows_cached_and_module_level(tmp_path):
    _, _, Recompile, *_ = _tools()
    findings = _run_pass(
        Recompile,
        """
        import functools
        import jax

        _step = jax.jit(lambda s: s)  # module level: compiled once

        @functools.lru_cache(maxsize=None)
        def make_step(mesh):
            return jax.jit(lambda s: s)  # cached builder

        @jax.jit
        def entry(tables):
            return pl.pallas_call(kernel)(tables)  # under the jit cache
        """,
        tmp_path,
    )
    assert findings == []


def test_recompile_flags_traced_branch_not_static(tmp_path):
    _, _, Recompile, *_ = _tools()
    findings = _run_pass(
        Recompile,
        """
        import functools
        import jax

        @functools.partial(jax.jit, static_argnums=(1,))
        def f(x, flag):
            if flag:          # static: fine
                x = x + 1
            if x.shape[0] > 2:  # shape: fine
                x = x * 2
            if x:             # traced: flagged
                x = x - 1
            return x
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "traced value" in findings[0].message
    assert "'x'" in findings[0].message or " x " in findings[0].message


def test_recompile_flags_aot_entry_built_per_flush(tmp_path):
    """TP: an AOT entry lowered+compiled inside the per-flush dispatch
    function rebuilds the executable every flush — the exact hazard the
    parallel/aot.py shape-bucket cache exists to prevent."""
    _, _, Recompile, *_ = _tools()
    findings = _run_pass(
        Recompile,
        """
        import jax

        def dispatch(state, rows, slots):
            exe = jax.jit(lambda s, r, i: s).lower(
                state, rows, slots
            ).compile()
            return exe(state, rows, slots)
        """,
        tmp_path,
    )
    assert len(findings) == 2  # the jit ctor AND the lower().compile()
    assert all("per call" in f.message for f in findings)


def test_recompile_aot_shape_bucket_cache_is_accepted(tmp_path):
    """TN/pragma: the production AOT pattern — lru_cache jitted builders
    plus a dict-probe entry cache whose build branch carries the reasoned
    recompile pragma (parallel/aot.py) — survives the pass clean, pinning
    that entries are built once per shape bucket, never per flush."""
    _, _, Recompile, *_ = _tools()
    findings = _run_pass(
        Recompile,
        """
        import functools
        import jax

        _ENTRIES = {}

        @functools.lru_cache(maxsize=None)
        def _fused_step(n_slots):
            return jax.jit(lambda s, r, i: s, donate_argnums=(0,))

        def call(key, build, *args):
            exe = _ENTRIES.get(key)
            if exe is None:
                # graftlint: recompile(built ONCE per shape-bucket key — the dict probe above IS the cache)
                exe = _ENTRIES[key] = build().lower(*args).compile()
            return exe(*args)
        """,
        tmp_path,
    )
    assert findings == []


# -- determinism ---------------------------------------------------------------


def test_determinism_flags_set_iteration(tmp_path):
    *_, Determinism, _ = _tools()
    findings = _run_pass(
        Determinism,
        """
        def routes(bindings, pending):
            ids = set(bindings) | set(pending)
            return {k: [] for k in ids}
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "no deterministic order" in findings[0].message


def test_determinism_flags_id_keyed_set_and_sort(tmp_path):
    *_, Determinism, _ = _tools()
    findings = _run_pass(
        Determinism,
        """
        def f(ops):
            bad = {id(op) for op in ops}
            ops.sort(key=lambda o: id(o))
            return bad
        """,
        tmp_path,
    )
    assert sorted(
        ("id()-keyed" in f.message, "sort keyed" in f.message)
        for f in findings
    ) == [(False, True), (True, False)]


def test_determinism_true_negatives(tmp_path):
    *_, Determinism, _ = _tools()
    findings = _run_pass(
        Determinism,
        """
        def g(bindings, pending):
            ids = set(bindings) | set(pending)
            ordered = sorted(ids)          # total order: fine
            n = len(ids)                   # order-free fold: fine
            hot = min(ids)                 # value-based: fine
            for k in ordered:              # iterating the sorted list
                n += k
            members = set(bindings)
            members.discard(0)             # membership only: fine
            return n, hot
        """,
        tmp_path,
    )
    assert findings == []


# -- wire-drift ----------------------------------------------------------------


def _opframe_text():
    with open(
        os.path.join(REPO, "fluidframework_tpu/protocol/opframe.py")
    ) as f:
        return f.read()


def test_wire_fingerprint_stable_under_formatting():
    *_, wd = _tools()
    text = _opframe_text()
    fp1 = wd.fingerprint_source(text)
    # whitespace/comment churn must NOT drift the fingerprint
    fp2 = wd.fingerprint_source("# a comment\n" + text + "\n\n# tail\n")
    assert wd.digest(fp1) == wd.digest(fp2)


def test_wire_fingerprint_trips_on_codec_field_change():
    *_, wd = _tools()
    text = _opframe_text()
    fp0 = wd.fingerprint_source(text)
    # 1) magic constant change
    mutated = text.replace("0x4F463152", "0x4F463153", 1)
    assert wd.digest(wd.fingerprint_source(mutated)) != wd.digest(fp0)
    # 2) struct layout change (a reordered/retyped pack string)
    assert "<iiiii" in text
    mutated = text.replace("<iiiii", "<iiiiq", 1)
    assert wd.digest(wd.fingerprint_source(mutated)) != wd.digest(fp0)


def test_wire_drift_gate_end_to_end(tmp_path):
    """A codec edit without --regen-fingerprints fails; regen (with its
    version bump) clears it."""
    core, *_, wd = _tools()
    from tools.graftlint import config
    from tools.graftlint.passes import WireDriftPass

    # fixture repo: one codec module + a lock generated from it
    rel = config.CODEC_MODULES[1]  # protocol/opframe.py
    mod_dir = tmp_path / os.path.dirname(rel)
    mod_dir.mkdir(parents=True)
    (tmp_path / "api-report").mkdir()
    (mod_dir / os.path.basename(rel)).write_text(_opframe_text())

    orig_root = config.REPO_ROOT
    config.REPO_ROOT = str(tmp_path)
    try:
        wd.regenerate(str(tmp_path))
        lock = wd.load_lock(str(tmp_path))
        assert lock["modules"][rel]["version"] == 1

        src = core.ModuleSource.load(str(tmp_path), rel)
        assert list(WireDriftPass().run(src)) == []  # clean

        # mutate the codec: drift must be reported
        mutated = _opframe_text().replace("0x4F463152", "0x4F463154", 1)
        (mod_dir / os.path.basename(rel)).write_text(mutated)
        src = core.ModuleSource.load(str(tmp_path), rel)
        findings = [f for f, _ in WireDriftPass().run(src)]
        assert len(findings) == 1
        assert "fingerprint drift" in findings[0].message
        assert "_RAW_MAGIC" in findings[0].message

        # accept: regen bumps the version and the check turns clean
        changed = wd.regenerate(str(tmp_path))
        assert rel in changed
        lock = wd.load_lock(str(tmp_path))
        assert lock["modules"][rel]["version"] == 2
        src = core.ModuleSource.load(str(tmp_path), rel)
        assert list(WireDriftPass().run(src)) == []
    finally:
        config.REPO_ROOT = orig_root


def test_committed_lock_matches_tree():
    """The committed wire_fingerprints.json must describe the current
    codec sources (the mechanical half of the compat-matrix gate)."""
    *_, wd = _tools()
    from tools.graftlint import config

    lock = wd.load_lock(REPO)["modules"]
    assert set(lock) == set(config.CODEC_MODULES)
    for rel, entry in lock.items():
        with open(os.path.join(REPO, rel)) as f:
            fp = wd.fingerprint_source(f.read(), rel)
        assert wd.digest(fp) == entry["digest"], (
            f"{rel} drifted from the committed fingerprint — run "
            "python -m tools.graftlint --regen-fingerprints in the same "
            "change that moves the wire format"
        )


# -- fault-site ----------------------------------------------------------------


def _fault_site_pass():
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.graftlint.passes import FaultSitePass

    return FaultSitePass


def test_fault_site_flags_unknown_site(tmp_path):
    findings = _run_pass(
        _fault_site_pass(),
        """
        from fluidframework_tpu.testing.faults import inject_fault

        @inject_fault("not.a.site")
        def append(log, frame):
            log.append(frame)
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "unknown injection site" in findings[0].message


def test_fault_site_flags_non_literal_name(tmp_path):
    findings = _run_pass(
        _fault_site_pass(),
        """
        from fluidframework_tpu.testing import faults

        SITE = "store.append"

        @faults.inject_fault(SITE)
        def append(log, frame):
            log.append(frame)
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "string literal" in findings[0].message


def test_fault_site_accepts_documented_vocabulary(tmp_path):
    findings = _run_pass(
        _fault_site_pass(),
        """
        from fluidframework_tpu.testing.faults import inject_fault

        @inject_fault("store.append")
        def append(log, frame):
            log.append(frame)

        @inject_fault("pump.dispatch")
        def dispatch(fleet, docs, rows):
            fleet.dispatch_staged(docs, rows)

        @inject_fault("pump.feed")
        def feed(backend):
            backend.pump_stage()
            return backend.pump_dispatch()

        @inject_fault("admission.decide")
        def decide(ctl, tenant, doc, n):
            return ctl.check(tenant, doc, n)

        @inject_fault("shed.tier")
        def evaluate(ctl, pressure):
            return ctl.tier_for(pressure)
        """,
        tmp_path,
    )
    assert findings == []


def test_fault_site_flags_unregistered_feed_site(tmp_path):
    """The r12 regression shape: a continuous-feed boundary added to a
    production module without declaring it in the vocabulary (e.g. a
    second ticker trigger named off-vocabulary) must fail lint — the
    deadline tick's recovery contract (rows stay buffered, next tick
    re-fires) only exists if the site is documented."""
    findings = _run_pass(
        _fault_site_pass(),
        """
        from fluidframework_tpu.testing.faults import inject_fault

        @inject_fault("pump.feed_tick")
        def feed_tick(backend):
            return backend.pump_dispatch()
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "unknown injection site" in findings[0].message
    assert "pump.feed_tick" in findings[0].message


def test_fault_site_flags_unregistered_overload_site(tmp_path):
    """The r13 regression shape: an overload boundary added to a
    production module without declaring it in the vocabulary (e.g. a
    second admission check named off-vocabulary) must fail lint — the
    fail-closed contract (op nacked, never silently admitted) only
    exists if the site is documented."""
    findings = _run_pass(
        _fault_site_pass(),
        """
        from fluidframework_tpu.testing.faults import inject_fault

        @inject_fault("admission.precheck")
        def precheck(ctl, tenant, doc):
            return ctl.check(tenant, doc, 1)
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "unknown injection site" in findings[0].message
    assert "admission.precheck" in findings[0].message


def test_fault_site_accepts_read_tier_sites(tmp_path):
    """The r15 read-tier sites — the batched snapshot gather and the
    encode-once fan-out write — are documented vocabulary: production
    boundaries decorated with them pass lint."""
    findings = _run_pass(
        _fault_site_pass(),
        """
        from fluidframework_tpu.testing.faults import inject_fault

        @inject_fault("read.gather")
        def gather(backend, idxs):
            return backend.fleet.doc_states_start(idxs)

        @inject_fault("push.fanout")
        def push_write(server, session, data):
            session.writer.write(data)
        """,
        tmp_path,
    )
    assert findings == []


def test_fault_site_flags_unregistered_read_site(tmp_path):
    """The r15 regression shape: a read-path boundary added to a
    production module without declaring it in the vocabulary (e.g. a
    second gather named off-vocabulary) must fail lint — the fallback
    contract (per-doc host gathers, counted) only exists if the site is
    documented."""
    findings = _run_pass(
        _fault_site_pass(),
        """
        from fluidframework_tpu.testing.faults import inject_fault

        @inject_fault("read.batch")
        def batch(backend, keys):
            return backend.doc_states(keys)
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "unknown injection site" in findings[0].message
    assert "read.batch" in findings[0].message


def test_fault_site_flags_unregistered_recovery(tmp_path):
    """A vocabulary entry whose recovery kind is not documented is a
    production site nobody catches — a lint failure, not a latent
    surprise."""
    from tools.graftlint.passes import fault_site

    vocab_dir = tmp_path / "fluidframework_tpu" / "testing"
    vocab_dir.mkdir(parents=True)
    (vocab_dir / "faults.py").write_text(
        'SITES = {"store.append": "wishful-thinking"}\n'
        'RECOVERY_KINDS = frozenset({"retry", "fallback"})\n'
    )
    p = fault_site.FaultSitePass()
    p.scope(str(tmp_path))  # pins the fixture root for vocabulary lookup
    src_dir = tmp_path / "mod"
    src_dir.mkdir()
    (src_dir / "m.py").write_text(
        "from fluidframework_tpu.testing.faults import inject_fault\n\n"
        '@inject_fault("store.append")\n'
        "def append(log, frame):\n"
        "    log.append(frame)\n"
    )
    core = _tools()[0]
    src = core.ModuleSource.load(str(tmp_path), "mod/m.py")
    findings = [f for f, _node in p.run(src)]
    assert len(findings) == 1
    assert "no registered recovery policy" in findings[0].message


def test_fault_vocabulary_is_fully_registered():
    """The REAL vocabulary: every production site maps to a documented
    recovery kind, and every site the service decorates is declared."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.graftlint import config as glconfig
    from tools.graftlint.passes import fault_site

    sites, kinds = fault_site._parse_vocabulary(
        os.path.join(REPO, glconfig.FAULT_VOCAB_MODULE)
    )
    assert sites, "vocabulary must not be empty"
    for site, recovery in sites.items():
        assert recovery in kinds, (site, recovery)
    # The parsed (static) vocabulary matches the runtime one.
    from fluidframework_tpu.testing import faults as runtime_faults

    assert sites == runtime_faults.SITES
    assert kinds == set(runtime_faults.RECOVERY_KINDS)


# -- baseline + CI invariant ---------------------------------------------------


def test_baseline_is_committed_empty():
    with open(os.path.join(REPO, "tools/graftlint/baseline.json")) as f:
        assert json.load(f) == []


def test_repo_is_graftlint_clean():
    """The CI gate: `python -m tools.graftlint --check` exits 0 on the
    merged tree (every surviving readback carries a reasoned pragma)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--check"],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_stale_baseline_entry_is_reported(tmp_path):
    core, *_ = _tools()
    baseline = [
        {"rule": "host-sync", "path": "gone.py", "source_line": "x = 1"}
    ]
    survivors, stale = core.apply_baseline([], baseline)
    assert survivors == []
    assert stale == baseline


# -- review-hardening regressions ----------------------------------------------


def test_determinism_flags_set_consumer_in_for_header(tmp_path):
    """`for k in list(ids):` hides the set inside a call in the loop
    header — the consumer check must still see it."""
    *_, Determinism, _ = _tools()
    findings = _run_pass(
        Determinism,
        """
        def f(ids_in):
            ids = set(ids_in)
            out = []
            for k in list(ids):
                out.append(k)
            for j, k in enumerate(ids, 1):
                out.append((j, k))
            return out
        """,
        tmp_path,
    )
    assert len(findings) == 2
    assert all("set" in f.message for f in findings)


def test_baseline_entries_suppress_one_occurrence_each():
    """A copy-pasted duplicate of a baselined line is a NEW finding."""
    core = _tools()[0]
    f = dict(rule="host-sync", path="a.py", col=1,
             message="m", source_line="x = np.asarray(pool.state.err)")
    findings = [
        core.Finding(line=10, **f),
        core.Finding(line=20, **f),
    ]
    baseline = [findings[0].baseline_key()]
    survivors, stale = core.apply_baseline(findings, baseline)
    assert len(survivors) == 1 and survivors[0].line == 20
    assert stale == []


def test_scope_files_matches_outside_package(tmp_path):
    """Scope globs are repo-root-relative: a pattern outside
    fluidframework_tpu/ must match files, not silently cover nothing."""
    core = _tools()[0]
    (tmp_path / "tools").mkdir()
    (tmp_path / "tools" / "x.py").write_text("a = 1\n")
    (tmp_path / "fluidframework_tpu").mkdir()
    (tmp_path / "fluidframework_tpu" / "y.py").write_text("b = 2\n")
    got = core.scope_files(
        str(tmp_path), ("tools/*.py", "fluidframework_tpu/*.py")
    )
    assert got == ["fluidframework_tpu/y.py", "tools/x.py"]


# -- r14 flight-recorder fixtures ----------------------------------------------


def test_fault_site_accepts_journal_dump_site(tmp_path):
    """The r14 flight-recorder dump boundary: ``journal.dump`` is in the
    documented vocabulary (recovery: a failed dump is counted and
    absorbed — the journal is best-effort), so a production module
    carrying the site passes lint."""
    findings = _run_pass(
        _fault_site_pass(),
        """
        from fluidframework_tpu.testing.faults import inject_fault

        @inject_fault("journal.dump")
        def write_dump(path, payload):
            with open(path, "w", encoding="utf-8") as f:
                f.write(payload)
        """,
        tmp_path,
    )
    assert findings == []


def test_fault_site_flags_unregistered_journal_site(tmp_path):
    """The r14 regression shape: a second journal boundary (e.g. an
    upload site) added off-vocabulary must fail lint — the absorb
    contract only exists if the site is documented."""
    findings = _run_pass(
        _fault_site_pass(),
        """
        from fluidframework_tpu.testing.faults import inject_fault

        @inject_fault("journal.upload")
        def upload_dump(path):
            return path
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "unknown injection site" in findings[0].message


def test_host_sync_flags_journal_producer_bare_transfer(tmp_path):
    """The flight recorder's zero-readback contract: the journal
    consumes HOST state only — the existing one-boxcar-stale scan and
    /metrics scrape data. A journal producer that runs its OWN
    device→host transfer to enrich an event is a new readback on the
    serving path; the fixture proves the host-sync pass fails it bare
    (and there is deliberately no blessed pragma shape for it: the fix
    is to consume already-transferred data, not to annotate)."""
    _, HostSync, *_ = _tools()
    findings = _run_pass(
        HostSync,
        """
        import numpy as np

        def journal_device_err(pool, journal):
            # WRONG: pulls the err lane synchronously just to journal it
            err = np.asarray(pool.state.err)
            journal.record("device.err", err_docs=int((err != 0).sum()))
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "device→host" in findings[0].message


# -- r16 serving-profiler fixtures ---------------------------------------------


def test_fault_site_accepts_profiler_arm_site(tmp_path):
    """The r16 profiler capture-arm boundary: ``profiler.arm`` is in the
    documented vocabulary (recovery: a failed arm is counted and
    absorbed — arm() returns False and /profilez 503s; the serving path
    never sees it), so a production module carrying the site passes
    lint."""
    findings = _run_pass(
        _fault_site_pass(),
        """
        from fluidframework_tpu.testing.faults import inject_fault

        @inject_fault("profiler.arm")
        def arm_window(duration_ms):
            return duration_ms
        """,
        tmp_path,
    )
    assert findings == []


def test_fault_site_flags_unregistered_profiler_site(tmp_path):
    """The r16 regression shape: a second profiler boundary (e.g. a
    capture-export site) added off-vocabulary must fail lint — the
    absorb contract only exists if the site is documented."""
    findings = _run_pass(
        _fault_site_pass(),
        """
        from fluidframework_tpu.testing.faults import inject_fault

        @inject_fault("profiler.capture")
        def export_window(path):
            return path
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "unknown injection site" in findings[0].message


# -- r17 loop-blocking ---------------------------------------------------------


def _loop_blocking_pass():
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.graftlint.passes import LoopBlockingPass

    return LoopBlockingPass


def test_loop_blocking_flags_sleep_and_transfer_in_coroutine(tmp_path):
    """TP: a time.sleep directly in a coroutine and a device readback in
    a sync helper the coroutine calls — both reachable from the loop,
    both flagged, the transitive path named in the message."""
    findings = _run_pass(
        _loop_blocking_pass(),
        """
        import time
        import numpy as np

        class S:
            async def ticker(self):
                time.sleep(0.01)
                self._step()

            def _step(self):
                return np.asarray(self.pool.state.err)
        """,
        tmp_path,
    )
    msgs = sorted(f.message for f in findings)
    assert len(findings) == 2, msgs
    assert any("time.sleep" in m for m in msgs)
    assert any(
        "device→host" in m and "ticker -> _step" in m for m in msgs
    )


def test_loop_blocking_off_loop_split_is_clean(tmp_path):
    """TN: the sanctioned pattern — the blocking transfer half runs via
    run_in_executor (the scan_transfer split); the off-loop helper's own
    np.asarray is NOT on-loop reachable."""
    findings = _run_pass(
        _loop_blocking_pass(),
        """
        import asyncio
        import numpy as np

        class S:
            async def tick(self, dev_backend):
                token = dev_backend.prefetch()
                loop = asyncio.get_running_loop()
                host = await loop.run_in_executor(
                    None, self.scan_transfer, token
                )
                return host

            @staticmethod
            def scan_transfer(token):
                return np.asarray(token.dev)
        """,
        tmp_path,
    )
    assert findings == []


def test_loop_blocking_flags_direct_off_loop_helper_call(tmp_path):
    """TP: calling a declared off-loop half synchronously from a
    coroutine defeats the split — flagged by name."""
    findings = _run_pass(
        _loop_blocking_pass(),
        """
        class S:
            async def tick(self):
                return self.scan_transfer(self._token)
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "off-loop helper scan_transfer()" in findings[0].message


def test_loop_blocking_loop_entry_roots_apply(tmp_path):
    """The cross-module on-loop contract: device_backend's ``flush`` is
    a configured LOOP_ENTRY root — a blocking op inside it is flagged
    with no async def in sight (network_server's loop calls it)."""
    findings = _run_pass(
        _loop_blocking_pass(),
        """
        import time

        class Backend:
            def flush(self):
                time.sleep(0.001)
        """,
        tmp_path,
        relpath="fluidframework_tpu/service/device_backend.py",
    )
    assert len(findings) == 1
    assert "time.sleep" in findings[0].message


def test_loop_blocking_onloop_pragma_suppresses_with_reason(tmp_path):
    snippet = """
    import numpy as np

    class S:
        async def drain(self):
            {pragma}
            err = np.asarray(self.pool.state.err)
            return err
    """
    bare = _run_pass(
        _loop_blocking_pass(), snippet.format(pragma="pass"), tmp_path
    )
    assert len(bare) == 1
    annotated = _run_pass(
        _loop_blocking_pass(),
        snippet.format(
            pragma="# graftlint: onloop(quiescence barrier — runs only "
            "after ingest went quiet)"
        ),
        tmp_path,
    )
    assert annotated == []


def test_loop_blocking_unbounded_lock_acquire(tmp_path):
    """TP: a bare .acquire() on a lock parks the loop behind any
    producer thread; TN: a timeout-bounded acquire."""
    findings = _run_pass(
        _loop_blocking_pass(),
        """
        class S:
            async def handle(self):
                self._lock.acquire()
                try:
                    return 1
                finally:
                    self._lock.release()

            async def bounded(self):
                return self._lock.acquire(timeout=0.1)
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "unbounded Lock.acquire" in findings[0].message


# -- r17 lock-order ------------------------------------------------------------


def _lock_order_pass():
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.graftlint.passes import LockOrderPass

    return LockOrderPass


def _run_lock_order(snippet, tmp_path, relpath="fluidframework_tpu/service/x.py"):
    core = _tools()[0]
    abspath = tmp_path / "snippet.py"
    abspath.write_text(textwrap.dedent(snippet))
    src = core.ModuleSource.load(str(tmp_path), "snippet.py")
    src.path = relpath
    p = _lock_order_pass()()
    run_findings = [
        f for f, node in p.run(src) if not src.suppressed(f, node)
    ]
    return run_findings, p.finalize()


def test_lock_order_cycle_detected(tmp_path):
    """TP: two code paths taking the same two locks in opposite order —
    the classic deadlock — is a cycle in the aggregated graph."""
    run_f, cycles = _run_lock_order(
        """
        class A:
            def f(self):
                with self._lock:
                    with self._ring_lock:
                        pass

            def g(self):
                with self._ring_lock:
                    with self._lock:
                        pass
        """,
        tmp_path,
    )
    assert run_f == []
    assert len(cycles) == 1
    assert "lock-order cycle" in cycles[0].message
    assert "A._lock" in cycles[0].message
    assert "A._ring_lock" in cycles[0].message


def test_lock_order_consistent_order_is_clean(tmp_path):
    """TN: the same nesting everywhere is an ordered pair — edges, but
    no cycle."""
    run_f, cycles = _run_lock_order(
        """
        class A:
            def f(self):
                with self._lock:
                    with self._ring_lock:
                        pass

            def g(self):
                with self._lock:
                    with self._ring_lock:
                        pass
        """,
        tmp_path,
    )
    assert run_f == []
    assert cycles == []


def test_lock_order_interprocedural_cycle(tmp_path):
    """The cycle hides behind a call: f holds L and calls helper (which
    takes M); g nests the other way. Still detected via the per-function
    acquire closures."""
    run_f, cycles = _run_lock_order(
        """
        class A:
            def f(self):
                with self._lock:
                    self._helper()

            def _helper(self):
                with self._ring_lock:
                    pass

            def g(self):
                with self._ring_lock:
                    with self._lock:
                        pass
        """,
        tmp_path,
    )
    assert len(cycles) == 1


def test_lock_order_gc_callback_taking_lock_fails(tmp_path):
    """TP: the exact r16 deadlock shape — a gc.callbacks hook that
    acquires a lock (directly or via a metric inc) fails lint."""
    run_f, _ = _run_lock_order(
        """
        import gc

        def _cb(phase, info):
            with _LOCK:
                pass

        gc.callbacks.append(_cb)
        """,
        tmp_path,
        relpath="fluidframework_tpu/telemetry/x.py",
    )
    assert len(run_f) == 1
    assert "must be lock-free by contract" in run_f[0].message

    run_f2, _ = _run_lock_order(
        """
        import gc

        def _cb(phase, info):
            pause_counter().inc(gen="0")

        gc.callbacks.append(_cb)
        """,
        tmp_path,
        relpath="fluidframework_tpu/telemetry/x.py",
    )
    assert len(run_f2) == 1
    assert "_Metric._lock" in run_f2[0].message


def test_lock_order_buffering_gc_callback_is_clean(tmp_path):
    """TN: the production contract — the callback only appends to a
    plain list (GIL-atomic) and normal code drains it."""
    run_f, cycles = _run_lock_order(
        """
        import gc
        import time

        _PENDING = []

        def _cb(phase, info):
            _PENDING.append((time.perf_counter(), info.get("generation")))

        gc.callbacks.append(_cb)
        """,
        tmp_path,
        relpath="fluidframework_tpu/telemetry/x.py",
    )
    assert run_f == [] and cycles == []


def test_lock_order_render_path_nested_hold_fails(tmp_path):
    """TP: a render path acquiring a second lock while holding one —
    the shape the r16 hardening removed (snapshot under the lock,
    render outside it)."""
    run_f, _ = _run_lock_order(
        """
        class MetricsRegistry:
            def render(self):
                with self._lock:
                    for m in self._metrics.values():
                        with m._lock:
                            pass
        """,
        tmp_path,
        relpath="fluidframework_tpu/telemetry/metrics.py",
    )
    assert len(run_f) == 1
    assert "ONE lock at a time" in run_f[0].message


def test_lock_order_self_deadlock(tmp_path):
    run_f, _ = _run_lock_order(
        """
        class A:
            def f(self):
                with self._lock:
                    with self._lock:
                        pass
        """,
        tmp_path,
    )
    assert len(run_f) == 1
    assert "self-deadlock" in run_f[0].message


def test_lock_order_pragma_suppresses_with_reason(tmp_path):
    run_f, _ = _run_lock_order(
        """
        class MetricsRegistry:
            def render(self):
                with self._lock:
                    # graftlint: lockorder(m is registry-private: no other path holds m._lock without the registry lock)
                    with self._m._lock:
                        pass
        """,
        tmp_path,
        relpath="fluidframework_tpu/telemetry/metrics.py",
    )
    assert run_f == []


# -- r17 vocab-drift ------------------------------------------------------------


def _vocab_pass():
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.graftlint.passes import VocabDriftPass

    return VocabDriftPass


def test_vocab_drift_flags_undeclared_journal_kind(tmp_path):
    findings = _run_pass(
        _vocab_pass(),
        """
        from fluidframework_tpu.telemetry import journal

        def submit(doc):
            journal.record("frame.submitted", doc=doc)
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "undeclared journal event kind 'frame.submitted'" in (
        findings[0].message
    )


def test_vocab_drift_accepts_declared_kinds_and_conditional(tmp_path):
    """TN: declared kinds pass, including the two-literal conditional
    shape the admission path uses."""
    findings = _run_pass(
        _vocab_pass(),
        """
        from fluidframework_tpu.telemetry import journal, profiler

        def submit(doc, admitted):
            journal.record("frame.submit", doc=doc)
            journal.record(
                "admission.admit" if admitted else "admission.deny",
                doc=doc,
            )
            profiler.record("host_stage", 0.0, 1.0)
        """,
        tmp_path,
    )
    assert findings == []


def test_vocab_drift_flags_undeclared_profiler_lane(tmp_path):
    findings = _run_pass(
        _vocab_pass(),
        """
        from fluidframework_tpu.telemetry import profiler

        def step(t0, t1):
            profiler.record("device_wait", t0, t1)
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "undeclared profiler lane 'device_wait'" in findings[0].message


def test_vocab_drift_flags_non_literal_kind(tmp_path):
    findings = _run_pass(
        _vocab_pass(),
        """
        from fluidframework_tpu.telemetry import journal

        def submit(kind, doc):
            journal.record(kind, doc=doc)
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "string literal" in findings[0].message


def test_vocab_drift_flags_unknown_stage_literal(tmp_path):
    findings = _run_pass(
        _vocab_pass(),
        """
        from fluidframework_tpu.telemetry import tracing

        def handle(traces):
            tracing.stamp(traces, "alfredo", "start")
            tracing.stamp(traces, "alfred", "end")
            tracing.stamp(traces, tracing.STAGE_DELI, "start")
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "'alfredo'" in findings[0].message


def test_vocab_drift_family_checks(tmp_path):
    """Undeclared family, kind mismatch, and non-literal name all fail;
    a declared registration passes."""
    findings = _run_pass(
        _vocab_pass(),
        """
        from fluidframework_tpu.telemetry import metrics

        def register(reg, name):
            ok = reg.counter("retry_attempts_total", "x", ("site",))
            bad_name = reg.counter("my_new_total", "x")
            bad_kind = reg.gauge("retry_attempts_total", "x")
            non_literal = reg.counter(name, "x")
            return ok, bad_name, bad_kind, non_literal
        """,
        tmp_path,
    )
    msgs = sorted(f.message for f in findings)
    assert len(findings) == 3, msgs
    assert any("undeclared Prometheus family 'my_new_total'" in m for m in msgs)
    assert any("one family, one kind" in m for m in msgs)
    assert any("must be a string literal" in m for m in msgs)


def test_vocab_drift_dead_fault_site(tmp_path):
    """The DEAD direction: a site declared in the vocabulary that no
    production boundary decorates fails via finalize()."""
    core = _tools()[0]
    vocab_dir = tmp_path / "fluidframework_tpu" / "testing"
    vocab_dir.mkdir(parents=True)
    (vocab_dir / "faults.py").write_text(
        'SITES = {"store.append": "retry", "store.ghost": "retry"}\n'
        'RECOVERY_KINDS = frozenset({"retry"})\n'
    )
    mod_dir = tmp_path / "fluidframework_tpu" / "service"
    mod_dir.mkdir(parents=True)
    (mod_dir / "m.py").write_text(textwrap.dedent(
        """
        from fluidframework_tpu.testing.faults import inject_fault

        @inject_fault("store.append")
        def append(log, frame):
            log.append(frame)
        """
    ))
    p = _vocab_pass()()
    p.scope(str(tmp_path))
    src = core.ModuleSource.load(
        str(tmp_path), "fluidframework_tpu/service/m.py"
    )
    run_findings = list(p.run(src))
    assert run_findings == []
    dead = [
        f for f in p.finalize()
        if "dead fault site" in f.message
    ]
    assert len(dead) == 1
    assert "'store.ghost'" in dead[0].message


def test_vocab_drift_repo_vocabularies_have_no_dead_entries():
    """The real repo: run the pass over its whole scope; finalize must
    find nothing dead (every site/kind/lane/stage/family has a live
    producer) — the CI invariant behind the empty baseline."""
    core = _tools()[0]
    p = _vocab_pass()()
    findings = []
    for rel in p.scope(REPO):
        src = core.ModuleSource.load(REPO, rel)
        findings.extend(f for f, _n in p.run(src))
    findings.extend(p.finalize())
    assert findings == [], [f.render() for f in findings]


# -- r17 stale pragmas + output formats ----------------------------------------


def test_stale_pragma_reported_and_live_pragma_kept(tmp_path):
    """A reasoned pragma whose finding no longer fires is itself a
    finding; a pragma still suppressing something is not."""
    core = _tools()[0]
    pkg = tmp_path / "fluidframework_tpu" / "parallel"
    pkg.mkdir(parents=True)
    (pkg / "fleet.py").write_text(textwrap.dedent(
        """
        import numpy as np

        def live(pool):
            return np.asarray(pool.state.err)  # graftlint: readback(explicit health pull)

        def stale(rows):
            return np.asarray(rows)  # graftlint: readback(this suppresses nothing)
        """
    ))
    findings, _ = core.run(
        str(tmp_path), passes=["host-sync"], use_baseline=False
    )
    assert [f.rule for f in findings] == ["stale-pragma"], [
        f.render() for f in findings
    ]
    assert findings[0].line == 8


def test_stale_pragma_not_reported_when_pass_not_selected(tmp_path):
    """A pragma is only stale when its OWN pass looked: running just the
    determinism pass must not call host-sync pragmas stale."""
    core = _tools()[0]
    pkg = tmp_path / "fluidframework_tpu" / "tree"
    pkg.mkdir(parents=True)
    (pkg / "m.py").write_text(
        "import numpy as np\n"
        "def f(rows):\n"
        "    return np.asarray(rows)  # graftlint: readback(unrelated)\n"
    )
    findings, _ = core.run(
        str(tmp_path), passes=["determinism"], use_baseline=False
    )
    assert findings == []


def test_repo_has_no_stale_pragmas():
    """The sweep satellite: the merged tree's reasoned-exception set is
    fully live (explicit --stale-pragmas mode exits 0)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--check",
         "--stale-pragmas"],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_json_output_shape():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--check",
         "--format=json", "--timings"],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["tool"] == "graftlint"
    assert doc["findings"] == []
    assert doc["stale_baseline_entries"] == []
    assert set(doc["pass_seconds"]) == {
        "host-sync", "recompile-hazard", "determinism", "fault-site",
        "wire-drift", "loop-blocking", "lock-order", "vocab-drift",
    }


def test_sarif_output_shape(tmp_path):
    """SARIF renders findings with ruleId + physical location (drive it
    through a fixture repo so there IS a finding)."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.graftlint.__main__ import _as_sarif

    core = _tools()[0]
    f = core.Finding(
        rule="loop-blocking", path="fluidframework_tpu/service/x.py",
        line=12, col=3, message="time.sleep blocks the event loop",
    )
    doc = _as_sarif([f])
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "graftlint"
    res = run["results"][0]
    assert res["ruleId"] == "loop-blocking"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "fluidframework_tpu/service/x.py"
    assert loc["region"]["startLine"] == 12


def test_all_eight_passes_registered():
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.graftlint.passes import ALL_PASSES

    assert [p.id for p in ALL_PASSES] == [
        "host-sync", "recompile-hazard", "determinism", "fault-site",
        "wire-drift", "loop-blocking", "lock-order", "vocab-drift",
    ]


def test_host_sync_flags_profiler_producer_bare_transfer(tmp_path):
    """The profiler's zero-readback contract: producers record HOST
    perf_counter timestamps only — device_step closes on the pump's
    EXISTING one-boxcar-stale scan. A producer that runs its own
    device→host transfer to 'time the device more precisely' is a new
    readback on the serving path; the fixture proves the host-sync pass
    fails it bare (no blessed pragma shape: the fix is to close on the
    existing scan, not to annotate)."""
    _, HostSync, *_ = _tools()
    findings = _run_pass(
        HostSync,
        """
        import numpy as np
        import time

        def profile_device_step(pool, profiler, t0):
            # WRONG: barriers the device just to close a timing lane
            np.asarray(pool.state.count)
            profiler.record("device_step", t0, time.perf_counter())
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "device→host" in findings[0].message


# -- r19 residency fixtures ----------------------------------------------------


def test_fault_site_accepts_residency_sites(tmp_path):
    """The r19 residency commit boundaries — ``doc.hibernate`` (the
    summarize→pointer walk already ran; this evicts the slots) and
    ``doc.wake`` (restore the cold states and unpark pending ops) —
    are documented vocabulary: production boundaries decorated with
    them pass lint."""
    findings = _run_pass(
        _fault_site_pass(),
        """
        from fluidframework_tpu.testing.faults import inject_fault

        @inject_fault("doc.hibernate")
        def hibernate_commit(backend, doc_id, idxs, states):
            return backend.fleet.evict_docs(idxs, states)

        @inject_fault("doc.wake")
        def wake_commit(backend, doc_id):
            for key, (state, head) in backend.cold_records(doc_id):
                backend.fleet.restore_doc(key, state)
        """,
        tmp_path,
    )
    assert findings == []


def test_fault_site_flags_unregistered_residency_site(tmp_path):
    """The r19 regression shape: a residency boundary added to a
    production module without declaring it in the vocabulary (e.g. a
    ``doc.freeze`` eviction variant) must fail lint — the
    stay-resident/retry contracts only exist if the site is
    documented."""
    findings = _run_pass(
        _fault_site_pass(),
        """
        from fluidframework_tpu.testing.faults import inject_fault

        @inject_fault("doc.freeze")
        def freeze(backend, doc_id):
            return backend.hibernate_doc(doc_id)
        """,
        tmp_path,
    )
    assert len(findings) == 1
    assert "unknown injection site" in findings[0].message
    assert "doc.freeze" in findings[0].message
