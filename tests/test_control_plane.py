"""Deli control plane (reference deli/lambda.ts:989+ control messages,
:884-893 unauthorized-Summarize nack, :136-150 op-events)."""

import pytest

from fluidframework_tpu.models.shared_map import SharedMap
from fluidframework_tpu.protocol.types import MessageType, NackMessage
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.local_server import LocalFluidService
from fluidframework_tpu.service.sequencer import DocumentSequencer


def drain(rts):
    busy = True
    while busy:
        busy = any(rt.process_incoming() for rt in rts if rt.connected)


def test_unauthorized_summarize_gets_403():
    svc = LocalFluidService()
    a = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
    # A second writer whose token lacks the summary scope.
    conn = svc.connect("doc", "write", scopes=("doc:read", "doc:write"))
    from fluidframework_tpu.protocol.types import DocumentMessage

    conn.submit(
        DocumentMessage(
            client_sequence_number=1,
            reference_sequence_number=conn.join_seq,
            type=MessageType.SUMMARIZE,
            contents={"handle": "x", "head": 1},
        )
    )
    assert conn.nacks and conn.nacks[0].content_code == 403
    # The authorized client still summarizes fine.
    a.get_channel("m").set("k", 1)
    drain([a])
    a.submit_summary()
    drain([a])
    assert svc.docs["doc"].latest_summary is not None


def test_update_dsn_advances_durable_floor():
    s = DocumentSequencer("d")
    s.join()
    msg = s.control({"type": "updateDSN", "dsn": 7})
    assert msg.type == MessageType.CONTROL
    assert s.durable_seq == 7
    s.control({"type": "updateDSN", "dsn": 3})  # never regresses
    assert s.durable_seq == 7


def test_nack_messages_maintenance_mode():
    svc = LocalFluidService()
    a = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
    b = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
    a.get_channel("m").set("k", 1)
    drain([a, b])

    svc.control("doc", {"type": "nackMessages", "enable": True, "code": 503})
    a.get_channel("m").set("k", 2)
    a.flush()
    a.process_incoming()  # 503 -> ops park offline, connection drops
    assert not a.connected
    assert a.get_channel("m").get("k") == 2  # optimistic view kept

    svc.control("doc", {"type": "nackMessages", "enable": False})
    a.reconnect()
    drain([a, b])
    assert a.get_channel("m").get("k") == b.get_channel("m").get("k") == 2


def test_no_client_triggers_service_summary():
    svc = LocalFluidService()
    a = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
    a.get_channel("m").set("k", 1)
    drain([a])
    assert not svc.docs["doc"].service_summaries
    a.disconnect()  # last client out -> NoClient + end-of-session summary
    doc = svc.docs["doc"]
    assert doc.op_log[-1].type == MessageType.NO_CLIENT
    assert doc.service_summaries, "NoClient must trigger a service summary"
    # Re-join resets the trigger: next full departure emits again.
    b = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
    b.disconnect()
    assert sum(1 for m in doc.op_log if m.type == MessageType.NO_CLIENT) == 2
