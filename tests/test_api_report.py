"""API-surface lock (SURVEY §2.8 API-Extractor analog): the public surface
must match the committed report — regenerate with
`python tools/api_report.py write` when a change is INTENTIONAL."""

import os
import sys


def test_api_surface_matches_report():
    tools = os.path.join(os.path.dirname(__file__), "..", "tools")
    sys.path.insert(0, tools)
    try:
        import api_report
    finally:
        sys.path.remove(tools)
    report_file = os.path.join(
        os.path.dirname(__file__), "..", "api-report",
        "fluidframework_tpu.api.txt",
    )
    with open(report_file) as f:
        want = f.read()
    got = api_report.public_surface()
    assert got == want, (
        "public API surface drifted from api-report/ — regenerate with "
        "`python tools/api_report.py write` ONLY if the change is intentional"
    )
