"""The continuous device pump (r10): double-buffered ingest ring + AOT
donated dispatch in ``DeviceFleetBackend``.

Pinned here: pump-vs-one-shot state parity on identical op streams (dense
and mesh fleets), ring-full backpressure, the in-flight-dispatch shutdown
drain (no lost, no duplicated ops), the zero-per-flush-tracing AOT
contract (entries built once per shape bucket, never per flush), the
one-health-scan-readback-per-round transfer contract, and the pump stage
vocabulary on the frame trace spine."""

import jax
import jax.numpy as jnp
import numpy as np

from fluidframework_tpu.parallel import aot
from fluidframework_tpu.parallel.mesh import make_mesh
from fluidframework_tpu.protocol.constants import (
    F_ARG,
    F_LEN,
    F_REF,
    F_SEQ,
    F_TYPE,
    OP_INSERT,
    OP_WIDTH,
)
from fluidframework_tpu.protocol.opframe import SeqFrame
from fluidframework_tpu.service.device_backend import DeviceFleetBackend
from fluidframework_tpu.telemetry import tracing


def _round_frames(n_ch, k, r):
    """One round's insert frames: contiguous seqs r*k+1..(r+1)*k per
    channel, inserts at position 0 (text reads back reversed)."""
    rows = np.zeros((n_ch, k, OP_WIDTH), np.int32)
    ar = np.arange(k, dtype=np.int32)
    rows[:, :, F_TYPE] = OP_INSERT
    rows[:, :, F_LEN] = 1
    rows[:, :, F_SEQ] = r * k + 1 + ar[None, :]
    rows[:, :, F_REF] = r * k
    rows[:, :, F_ARG] = r * k + 1 + ar[None, :]
    texts = tuple(chr(97 + (r * k + i) % 26) for i in range(k))
    return rows, texts


def _feed(be, n_ch, k, r):
    rows, texts = _round_frames(n_ch, k, r)
    for i in range(n_ch):
        be.enqueue_frame(f"d{i}", SeqFrame("s", 0, 1, rows[i], texts, 0.0))


def _assert_state_parity(a: DeviceFleetBackend, b: DeviceFleetBackend):
    assert sorted(a.fleet.pools) == sorted(b.fleet.pools)
    for cap, pool_a in a.fleet.pools.items():
        pool_b = b.fleet.pools[cap]
        for name, x, y in zip(
            pool_a.state._fields, pool_a.state, pool_b.state
        ):
            assert bool(jnp.array_equal(x, y)), (cap, name)


def _run_rounds(be, n_ch, k, rounds, continuous):
    for r in range(rounds):
        _feed(be, n_ch, k, r)
        if continuous:
            be.pump_stage()
            be.pump_dispatch()
        else:
            be.flush()
    if continuous:
        be.pump_drain()
    else:
        be.flush()
        be.collect_now()


def test_pump_parity_dense():
    """Identical op streams through the pump (continuous stage/dispatch)
    and the legacy one-shot flush path converge to bit-identical pool
    states, the same applied totals, and the same served text."""
    n_ch, k, rounds = 6, 4, 5
    pump = DeviceFleetBackend(capacity=64, pump_mode=True)
    oneshot = DeviceFleetBackend(capacity=64, pump_mode=False)
    _run_rounds(pump, n_ch, k, rounds, continuous=True)
    _run_rounds(oneshot, n_ch, k, rounds, continuous=False)
    assert pump.ops_applied == oneshot.ops_applied == n_ch * k * rounds
    _assert_state_parity(pump, oneshot)
    assert pump.text("d0", "s") == oneshot.text("d0", "s")
    assert len(pump.text("d0", "s")) == k * rounds
    assert pump.stats()["docs_with_errors"] == 0


def test_pump_parity_mesh():
    """Same parity pin on the mesh fleet (the 8-device virtual CPU mesh
    from conftest): the pump's AOT shard_map dispatch and the one-shot
    path produce bit-identical sharded pool states."""
    mesh = make_mesh()
    n_ch, k, rounds = 16, 4, 3
    pump = DeviceFleetBackend(capacity=64, mesh=mesh, pump_mode=True)
    oneshot = DeviceFleetBackend(capacity=64, mesh=mesh, pump_mode=False)
    _run_rounds(pump, n_ch, k, rounds, continuous=True)
    _run_rounds(oneshot, n_ch, k, rounds, continuous=False)
    assert pump.ops_applied == oneshot.ops_applied == n_ch * k * rounds
    _assert_state_parity(pump, oneshot)
    assert pump.text("d3", "s") == oneshot.text("d3", "s")


def test_ring_full_backpressure():
    """Staging past the ring depth dispatches the oldest slot first: at
    most ``ring_depth`` uploads are ever in flight, the backpressure
    counter records the squeeze, and nothing is lost."""
    n_ch, k = 4, 4
    be = DeviceFleetBackend(capacity=64, pump_mode=True, ring_depth=2)
    for r in range(3):
        _feed(be, n_ch, k, r)
        be.pump_stage()  # stage only — no dispatch between rounds
    assert len(be._ring) == 2  # third stage squeezed the oldest slot out
    assert be.pump_backpressure == 1
    assert be.pump_dispatches == 1
    be.pump_drain()
    assert len(be._ring) == 0
    assert be._scan_token is None
    assert be.ops_applied == n_ch * k * 3
    assert be.text("d0", "s") == be.text("d1", "s")
    assert len(be.text("d0", "s")) == k * 3


def test_drain_with_inflight_dispatch_no_lost_or_dup_ops():
    """Shutdown drain with a dispatch in flight: rows staged behind an
    unconsumed health scan all land exactly once, and at-least-once
    redelivery of already-staged rows is dropped by the watermarks (no
    duplicate application)."""
    n_ch, k = 3, 4
    be = DeviceFleetBackend(capacity=64, pump_mode=True)
    ref = DeviceFleetBackend(capacity=64, pump_mode=False)
    _feed(be, n_ch, k, 0)
    be.pump_stage()
    be.pump_dispatch()  # dispatch round 0; its scan is now in flight
    assert be._scan_token is not None
    _feed(be, n_ch, k, 0)  # full replay of round 0: must drop whole
    _feed(be, n_ch, k, 1)  # fresh round staged behind the in-flight scan
    be.pump_stage()
    be.pump_drain()
    assert be.ops_applied == n_ch * k * 2  # no lost, no duplicated rows
    for r in range(2):
        _feed(ref, n_ch, k, r)
        ref.flush()
    ref.collect_now()
    _assert_state_parity(be, ref)


def test_aot_entries_built_once_per_shape_bucket():
    """The zero-per-flush-tracing contract: after one warm flush per
    shape bucket, steady-state flushes are pure AOT cache hits — calls
    grow, builds do not."""
    n_ch, k = 4, 4
    be = DeviceFleetBackend(capacity=64, pump_mode=True)
    _feed(be, n_ch, k, 0)
    be.flush()  # warm: builds the fused entry for this bucket
    warm = aot.stats()
    rounds = 5
    for r in range(1, rounds + 1):
        _feed(be, n_ch, k, r)
        be.flush()
    steady = aot.stats()
    assert steady["builds"] == warm["builds"], (
        "steady-state flushes must not build AOT entries "
        f"(warm={warm}, steady={steady})"
    )
    assert steady["calls"] >= warm["calls"] + rounds  # pure cache hits


def test_pump_round_is_one_scan_readback(monkeypatch):
    """The pump's transfer contract: a steady round performs EXACTLY one
    device→host transfer — consuming the previous round's health scan —
    and no synchronous np.asarray readback anywhere in the dispatch
    path."""
    from fluidframework_tpu.parallel import fleet as fleet_mod
    from fluidframework_tpu.service import device_backend as db_mod

    n_ch, k = 4, 4
    be = DeviceFleetBackend(capacity=64, pump_mode=True)
    _feed(be, n_ch, k, 0)
    be.flush()  # warm + leave a scan in flight

    transfers = []

    def _shim(mod):
        real_np = mod.np

        class _CountingNp:
            def __getattr__(self, name):
                return getattr(np, name)

            @staticmethod
            def asarray(*a, **kw):
                if a and isinstance(a[0], jax.Array):
                    transfers.append(("asarray", mod.__name__))
                return real_np.asarray(*a, **kw)

            @staticmethod
            def array(*a, **kw):
                if a and isinstance(a[0], jax.Array):
                    transfers.append(("array", mod.__name__))
                return real_np.array(*a, **kw)

        monkeypatch.setattr(mod, "np", _CountingNp())

    _shim(fleet_mod)
    _shim(db_mod)
    for r in range(1, 4):
        before = len(transfers)
        _feed(be, n_ch, k, r)
        be.pump_stage()
        be.pump_dispatch()
        got = transfers[before:]
        assert len(got) == 1, f"round {r}: {got}"  # the one stale scan


def test_pump_trace_spans_cover_stage_vocabulary():
    """Sampled frames riding the pump carry the r10 stage vocabulary:
    ring_stage (host assembly + async upload), device_step (the AOT
    dispatch call), scan_consume (the stale-scan readback wait) — and
    the legacy device/device_commit spans still bracket them."""
    n_ch, k = 2, 4
    be = DeviceFleetBackend(capacity=64, pump_mode=True)
    traces: list = []
    tracing.stamp(traces, tracing.STAGE_DEVICE, "start")
    be.track_trace(traces)
    _feed(be, n_ch, k, 0)
    be.flush()
    be.collect_now()  # consumes the scan: closes scan_consume + commit
    sp = tracing.spans(traces)
    for stage in (
        tracing.STAGE_RING_STAGE,
        tracing.STAGE_DEVICE_STEP,
        tracing.STAGE_SCAN_CONSUME,
        tracing.STAGE_DEVICE,
        tracing.STAGE_DEVICE_COMMIT,
    ):
        assert f"{stage}_ms" in sp, (stage, sp)
    # The observability registry accepts the new vocabulary.
    from fluidframework_tpu.telemetry import metrics

    reg = metrics.MetricsRegistry()
    metrics.observe_stage_spans(sp, reg)
    hist = reg.get("serving_stage_ms")
    assert hist.count(stage="ring_stage") == 1
    assert hist.count(stage="device_step") == 1
    assert hist.count(stage="scan_consume") == 1


def test_pipeline_pump_matches_oneshot_service():
    """Pipeline-level parity: the same client traffic through a pump
    service and a one-shot service serves identical device text (the
    production wiring of ``device_pump``)."""
    from fluidframework_tpu.models.shared_string import SharedString
    from fluidframework_tpu.runtime.container import ContainerRuntime
    from fluidframework_tpu.service.pipeline import PipelineFluidService

    texts = {}
    for pump in (True, False):
        svc = PipelineFluidService(n_partitions=2, device_pump=pump)
        rt = ContainerRuntime(svc, "doc", channels=(SharedString("s"),))
        s = rt.get_channel("s")
        s.insert_text(0, "pump parity")
        rt.flush()
        while rt.process_incoming():
            pass
        s.remove_range(0, 5)
        rt.flush()
        while rt.process_incoming():
            pass
        assert svc.device.pump_mode is pump
        texts[pump] = svc.device_text("doc", "s")
    assert texts[True] == texts[False] == "parity"


def test_pump_promotion_reroutes_staged_rows():
    """A doc that crosses its tier's high-water mark mid-stream promotes
    off the one-boxcar-stale scan, and rows staged before the promotion
    was consumed re-route to the new pool at dispatch time (slots resolve
    at dispatch, not at stage)."""
    n_ch, k, rounds = 2, 8, 8
    pump = DeviceFleetBackend(capacity=16, max_capacity=256, pump_mode=True)
    oneshot = DeviceFleetBackend(
        capacity=16, max_capacity=256, pump_mode=False
    )
    _run_rounds(pump, n_ch, k, rounds, continuous=True)
    _run_rounds(oneshot, n_ch, k, rounds, continuous=False)
    assert pump.fleet.migrations > 0  # the stream really promoted
    assert pump.ops_applied == oneshot.ops_applied == n_ch * k * rounds
    _assert_state_parity(pump, oneshot)
    assert len(pump.text("d0", "s")) == k * rounds
