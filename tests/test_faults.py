"""Chaos suite: the seeded fault matrix over every named injection site.

The r11 robustness contract (docs/failure-semantics.md): with a fault
injected at any stage boundary the trace spine names — store append, queue
send, pump stage/feed/dispatch, websocket delivery, lease acquire/renew — the
pipeline's wired recovery (retry / fallback / requeue / drain / fence)
must reproduce the un-faulted run BIT-IDENTICALLY: same device text, same
device lane state, same sequenced-op identity list, zero lost and zero
duplicate sequenced ops. And no recovery is silent: every cell asserts
its ``retry_attempts_total{site,outcome}`` /
``faults_injected_total{site,kind}`` increments.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from fluidframework_tpu.ops.segment_state import SegmentState
from fluidframework_tpu.protocol.constants import (
    F_ARG,
    F_LEN,
    F_REF,
    F_SEQ,
    F_TYPE,
    MAX_WRITERS,
    OP_INSERT,
    OP_WIDTH,
)
from fluidframework_tpu.protocol.opframe import OpFrame, SeqFrame
from fluidframework_tpu.protocol.types import (
    DocumentMessage,
    MessageType,
    NackErrorType,
    NackMessage,
)
from fluidframework_tpu.service.device_backend import DeviceFleetBackend
from fluidframework_tpu.service.multinode import MultiNodeFluidService
from fluidframework_tpu.service.pipeline import PipelineFluidService
from fluidframework_tpu.telemetry import metrics
from fluidframework_tpu.testing import faults

MINT = 1 << 14  # shared_string._MINT_STRIDE: content ids scope per conn_no
ALPHA = "abcdefghijklmnopqrstuvwxyz"


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(autouse=True)
def _flight_recorder(tmp_path):
    """The chaos harness's artifact contract (r14): the flight recorder
    dumps into the test artifact dir on any parity failure (and on the
    fatal/exhausted outcomes the matrix provokes), so "bit-exact
    assertion failed" ships with the event stream that explains it."""
    import os

    from fluidframework_tpu.telemetry import journal

    journal.enable()
    journal.configure(
        dump_dir=os.environ.get("TEST_ARTIFACT_DIR") or str(tmp_path)
    )
    journal.reset()
    yield
    journal.JOURNAL.dump_dir = None
    journal.reset()


def _assert_parity(state, ref, label):
    """Bit-exact post-recovery parity, with the r14 post-mortem: a miss
    auto-dumps the journal before failing the test."""
    if state != ref:
        from fluidframework_tpu.telemetry import journal

        path = journal.auto_dump("chaos-parity")
        raise AssertionError(
            f"{label} diverged from unfaulted run; journal dump: {path}"
        )


def _recovery_total(site, outcome=None) -> float:
    c = metrics.REGISTRY.get("retry_attempts_total")
    if c is None:
        return 0.0
    total = 0.0
    for key, _suffix, value in c.samples():
        d = dict(key)
        if d.get("site") == site and (
            outcome is None or d.get("outcome") == outcome
        ):
            total += value
    return total


# ---------------------------------------------------------------------------
# Primitives: the registry, policies, and the unified retry semantics


class TestPrimitives:
    def test_unknown_site_rejected(self):
        with pytest.raises(ValueError):
            faults.arm("not.a.site", faults.FailN(1))
        with pytest.raises(ValueError):
            faults.inject_fault("not.a.site")

    def test_fail_prob_schedule_is_seeded(self):
        a = faults.FailProb(0.5, seed=3)
        b = faults.FailProb(0.5, seed=3)
        assert [a.plan() for _ in range(64)] == [
            b.plan() for _ in range(64)
        ]

    def test_retry_outcome_vocabulary(self):
        from fluidframework_tpu.service.retry import (
            RetryPolicy,
            call_with_retry,
        )
        from fluidframework_tpu.telemetry.metrics import MetricsRegistry

        reg = MetricsRegistry()
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("transient")
            return "done"

        out = call_with_retry(
            "queue.send", flaky, policy=RetryPolicy(max_attempts=4),
            sleep=lambda _d: None, registry=reg,
        )
        assert out == "done"
        c = reg.get("retry_attempts_total")
        # Only attempts that scheduled a follow-up count as ``retry``.
        assert c.value(site="queue.send", outcome="retry") == 2
        assert c.value(site="queue.send", outcome="ok") == 1

        def always():
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            call_with_retry(
                "queue.send", always, policy=RetryPolicy(max_attempts=3),
                sleep=lambda _d: None, registry=reg,
            )
        assert c.value(site="queue.send", outcome="exhausted") == 1
        assert c.value(site="queue.send", outcome="retry") == 2 + 2

    def test_injected_crash_is_fatal_not_retried(self):
        from fluidframework_tpu.service.retry import call_with_retry
        from fluidframework_tpu.telemetry.metrics import MetricsRegistry

        reg = MetricsRegistry()
        calls = []

        def crashy():
            calls.append(1)
            raise faults.InjectedCrash("queue.send", "crash")

        with pytest.raises(faults.InjectedCrash):
            call_with_retry(
                "queue.send", crashy, sleep=lambda _d: None, registry=reg,
            )
        assert len(calls) == 1, "a crash must never retry in place"
        c = reg.get("retry_attempts_total")
        assert c.value(site="queue.send", outcome="fatal") == 1

    def test_deadline_budget_bounds_retries(self):
        from fluidframework_tpu.service.retry import (
            RetryPolicy,
            call_with_retry,
        )
        from fluidframework_tpu.telemetry.metrics import MetricsRegistry

        reg = MetricsRegistry()

        def always():
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            call_with_retry(
                "queue.send", always,
                policy=RetryPolicy(
                    max_attempts=100, base_delay_s=10.0, deadline_s=0.001
                ),
                sleep=lambda _d: None, registry=reg,
            )
        c = reg.get("retry_attempts_total")
        assert c.value(site="queue.send", outcome="exhausted") == 1
        assert c.value(site="queue.send", outcome="retry") == 0

    def test_unarmed_site_passes_through(self):
        @faults.inject_fault("queue.send")
        def f(x):
            return x + 1

        assert f(1) == 2
        assert faults.REGISTRY.invocations.get("queue.send") is None


# ---------------------------------------------------------------------------
# The standard workload + capture (the parity oracle)


def _submit(conn, frame):
    """Submit with the documented crash recovery: the harness plays the
    restart supervisor / reconnecting client — resubmitting the SAME
    frame after an injected fault is the real client behavior, and csn
    dedup at deli absorbs whatever half-landed. An admission throttle
    (r13: the frame was DENIED ahead of sequencing and nacked, nothing
    half-landed) resubmits the same way — the nack-recovery client
    contract."""
    for _ in range(8):
        try:
            conn.submit_frame(frame)
        except faults.InjectedFault:
            continue
        if conn.nacks:
            throttles = [
                n for n in conn.nacks
                if n.error_type == NackErrorType.THROTTLING
            ]
            assert len(throttles) == len(conn.nacks), conn.nacks
            conn.nacks.clear()
            continue
        return
    raise AssertionError("fault policy did not clear within 8 resubmits")


def _run_chaos_workload(arm=None, n_rounds=4, k=3):
    """Three writers over two documents submit deterministic insert
    frames; returns the post-drain canonical state."""
    svc = PipelineFluidService(n_partitions=2, checkpoint_every=4)
    conns = {
        "chaos-a": [svc.connect("chaos-a"), svc.connect("chaos-a")],
        "chaos-b": [svc.connect("chaos-b")],
    }
    if arm is not None:
        arm()
    csn = {}
    for r in range(n_rounds):
        for doc, cs in conns.items():
            for ci, conn in enumerate(cs):
                c0 = csn.get((doc, ci), 0) + 1
                origs = [conn.conn_no * MINT + c0 + j for j in range(k)]
                texts = [
                    ALPHA[(r + ci + j) % 26] * (1 + (j % 2))
                    for j in range(k)
                ]
                frame = OpFrame.build(
                    "s", ["ins"] * k, [0] * k, origs, texts,
                    csn0=c0, ref=svc.doc_head(doc),
                )
                _submit(conn, frame)
                csn[(doc, ci)] = c0 + k - 1
    faults.disarm()
    svc.pump()
    svc.flush_device()
    return _capture(svc, ["chaos-a", "chaos-b"])


def _capture(svc, docs):
    state = {}
    for d in docs:
        deltas = svc.get_deltas(d)
        seqs = [m.sequence_number for m in deltas]
        head = svc.doc_head(d)
        # Zero lost, zero duplicate sequenced ops: the durable log is a
        # gapless 1..head run.
        assert seqs == list(range(1, head + 1)), (d, seqs[:5], head)
        state[d] = {
            "text": svc.device_text(d, "s"),
            "idents": [
                (m.client_id, m.client_sequence_number, m.type)
                for m in deltas
            ],
            "summary": svc.device.channel_summary(d, "s"),
            "head": head,
        }
    return state


_REF = {}


def _reference_state():
    if "state" not in _REF:
        _REF["state"] = _run_chaos_workload(None)
    return _REF["state"]


def _policy(kind: str) -> faults.FaultPolicy:
    if kind == "fail":
        return faults.FailN(1)
    return faults.CrashAt(kind.split("_", 1)[1], times=1)


MATRIX = [
    (site, kind)
    for site in (
        "store.append", "queue.send", "pump.stage", "pump.feed",
        "pump.dispatch",
        # r13, the overload envelope: a faulted admission check fails
        # CLOSED (the op is nacked and the client resubmits — never
        # silently admitted, never dropped), and a faulted tier
        # evaluation holds the last tier — both must reproduce the
        # un-faulted run bit-identically.
        "admission.decide", "shed.tier",
    )
    for kind in ("fail", "crash_before", "crash_after")
]


class TestChaosMatrix:
    @pytest.mark.parametrize("site,kind", MATRIX)
    def test_post_recovery_state_parity(self, site, kind):
        ref = _reference_state()
        pre_recovery = _recovery_total(site)
        state = _run_chaos_workload(
            arm=lambda: faults.arm(site, _policy(kind))
        )
        assert faults.REGISTRY.injected_total(site) == 1, faults.stats()
        _assert_parity(state, ref, f"{site}/{kind}")
        # No silent recovery: the unified counter family moved for this
        # site (retry/ok for retried sites, fallback/requeue for the
        # pump, fatal for crashes that propagate to the supervisor).
        assert _recovery_total(site) > pre_recovery, (
            site, kind, metrics.REGISTRY.snapshot().get("retry_attempts_total"),
        )

    def test_fault_mix_across_all_sites(self):
        """Seeded probabilistic mix on every retried/fallback site at
        once — the matrix cells compose."""
        ref = _reference_state()

        def arm():
            for i, site in enumerate(
                ("store.append", "queue.send", "pump.dispatch")
            ):
                faults.arm(site, faults.FailProb(0.15, seed=41 + i))

        state = _run_chaos_workload(arm=arm)
        _assert_parity(state, ref, "fault-mix")
        assert faults.REGISTRY.injected_total() > 0

    def test_crashed_admission_check_fails_closed_with_nack(self):
        """The r13 overload row, spelled out: a CRASHED admission check
        — even crash-after, where the inner decision computed and only
        the ack was lost — denies and NACKS with ThrottlingError +
        retry_after; the op is never silently admitted and never
        dropped (the resubmit sequences it exactly once)."""
        svc = PipelineFluidService(n_partitions=2)
        conn = svc.connect("fc-doc")
        head = svc.doc_head("fc-doc")
        frame = OpFrame.build(
            "s", ["ins"], [0], [conn.conn_no * MINT + 1], ["x"],
            csn0=1, ref=head,
        )
        faults.arm("admission.decide", faults.CrashAt("after"))
        conn.submit_frame(frame)
        faults.disarm()
        assert svc.doc_head("fc-doc") == head, "silently admitted"
        assert conn.nacks, "fail-closed denial must nack, not drop"
        nk = conn.nacks[0]
        assert nk.error_type == NackErrorType.THROTTLING
        assert nk.content_code == 429 and nk.retry_after_s > 0
        conn.nacks.clear()
        conn.submit_frame(frame)  # the client contract: resubmit
        assert svc.doc_head("fc-doc") == head + 1
        seqs = [
            m.sequence_number for m in svc.get_deltas("fc-doc")
        ]
        assert seqs == list(range(1, head + 2))

    def test_injected_faults_visible_on_metrics(self):
        faults.arm("queue.send", faults.FailN(1))
        _run_chaos_workload()
        rendered = metrics.REGISTRY.render()
        assert "faults_injected_total" in rendered
        assert 'site="queue.send"' in rendered


# ---------------------------------------------------------------------------
# Pump-specific recovery: backpressure × dispatch failure, crash requeue


N_CH, K = 24, 8


def _feed_backend(be, r: int, n_ch: int = N_CH, k: int = K) -> None:
    ar = np.arange(k, dtype=np.int32)
    for i in range(n_ch):
        rows = np.zeros((k, OP_WIDTH), np.int32)
        rows[:, F_TYPE] = OP_INSERT
        rows[:, F_LEN] = 1
        rows[:, F_SEQ] = r * k + 1 + ar
        rows[:, F_REF] = r * k
        rows[:, F_ARG] = r * k + 1 + ar
        be.enqueue_frame(f"d{i}", SeqFrame("s", 0, 1, rows, (), 0.0))


def _make_backend() -> DeviceFleetBackend:
    return DeviceFleetBackend(
        capacity=128, max_batch=1 << 20, pump_mode=True, ring_depth=1
    )


def _pool_parity(a: DeviceFleetBackend, b: DeviceFleetBackend) -> None:
    assert sorted(a.fleet.pools) == sorted(b.fleet.pools)
    for cap, pa in a.fleet.pools.items():
        pb = b.fleet.pools[cap]
        for name, x, y in zip(SegmentState._fields, pa.state, pb.state):
            assert bool(jnp.array_equal(x, y)), (
                f"faulted/unfaulted divergence: pool {cap} lane {name}"
            )


class TestPumpChaos:
    def _reference(self, rounds: int) -> DeviceFleetBackend:
        ref = _make_backend()
        for r in range(rounds):
            _feed_backend(ref, r)
            ref.pump_stage()
        ref.pump_drain()
        return ref

    def test_backpressure_with_dispatch_failure_keeps_boxcar(self):
        """The r11 audit: ring-full backpressure forces the oldest slot to
        dispatch first; when THAT dispatch faults, the fallback applies
        the slot from its retained host copy — the staged boxcar is never
        dropped, and both counters tell the story."""
        be = _make_backend()
        _feed_backend(be, 0)
        be.pump_stage()  # ring (depth 1) now full
        _feed_backend(be, 1)
        pre_bp = be.pump_backpressure
        pre_fb = _recovery_total("pump.dispatch", "fallback")
        faults.arm("pump.dispatch", faults.FailN(1))
        be.pump_stage()  # backpressure dispatch -> injected failure -> fallback
        faults.disarm()
        assert be.pump_backpressure == pre_bp + 1
        assert _recovery_total("pump.dispatch", "fallback") == pre_fb + 1
        be.pump_drain()
        stats = be.stats()
        assert stats["ops_applied"] == 2 * N_CH * K
        assert stats["docs_with_errors"] == 0
        _pool_parity(be, self._reference(2))

    def test_crash_before_dispatch_requeues_slot_for_drain(self):
        """Extend the r10 drain contract to the injected-crash case: a
        crash at the dispatch boundary (before the device step ran) puts
        the slot back at the ring head, and one drain replays it with no
        lost/dup ops."""
        be = _make_backend()
        _feed_backend(be, 0)
        be.pump_stage()
        pre_rq = _recovery_total("pump.dispatch", "requeue")
        faults.arm("pump.dispatch", faults.CrashAt("before"))
        with pytest.raises(faults.InjectedCrash):
            be.pump_dispatch()
        faults.disarm()
        assert len(be._ring) == 1, "crashed slot must be requeued"
        assert _recovery_total("pump.dispatch", "requeue") == pre_rq + 1
        be.pump_drain()
        stats = be.stats()
        assert stats["ops_applied"] == N_CH * K
        assert stats["docs_with_errors"] == 0
        _pool_parity(be, self._reference(1))

    def test_crash_after_dispatch_does_not_requeue(self):
        """A crash AFTER the device step leaves the applied state
        authoritative: requeueing would double-apply, so the slot is
        consumed and the drain just barriers the scan."""
        be = _make_backend()
        _feed_backend(be, 0)
        be.pump_stage()
        faults.arm("pump.dispatch", faults.CrashAt("after"))
        with pytest.raises(faults.InjectedCrash):
            be.pump_dispatch()
        faults.disarm()
        assert len(be._ring) == 0, "completed slot must not replay"
        be.pump_drain()
        assert be.stats()["ops_applied"] == N_CH * K
        _pool_parity(be, self._reference(1))

    @pytest.mark.parametrize("boundary", ["before", "after"])
    def test_crash_at_stage_boundary_drains_clean(self, boundary):
        be = _make_backend()
        _feed_backend(be, 0)
        faults.arm("pump.stage", faults.CrashAt(boundary))
        with pytest.raises(faults.InjectedCrash):
            be.flush()
        faults.disarm()
        be.pump_drain()
        assert be.stats()["ops_applied"] == N_CH * K
        _pool_parity(be, self._reference(1))

    def test_feed_tick_crash_leaves_rows_buffered_next_tick_refires(self):
        """The r12 ``pump.feed`` recovery contract: a crashed deadline
        tick leaves every row buffered, the crash is counted (requeue,
        never silent), and the NEXT tick re-fires over exactly those
        rows — no op lost, none duplicated, state bit-identical to an
        unfaulted run."""
        be = DeviceFleetBackend(
            capacity=128, max_batch=1 << 20, pump_mode=True,
            ring_depth=1, feed_deadline_ms=0.0,
        )
        _feed_backend(be, 0)
        pre_rq = _recovery_total("pump.feed", "requeue")
        faults.arm("pump.feed", faults.CrashAt("before"))
        with pytest.raises(faults.InjectedCrash):
            be.pump_feed_counted()
        faults.disarm()
        assert be.stats()["ops_applied"] == 0
        assert be.needs_flush(), "crashed tick must leave rows buffered"
        assert _recovery_total("pump.feed", "requeue") == pre_rq + 1
        be.pump_feed_counted()  # the next tick re-fires
        be.pump_drain()
        stats = be.stats()
        assert stats["ops_applied"] == N_CH * K
        assert stats["docs_with_errors"] == 0
        _pool_parity(be, self._reference(1))

    def test_feed_tick_crash_after_is_fatal_not_refired(self):
        """Crash AFTER the feed ran: the boxcar dispatched and only the
        ack was lost — counted fatal, nothing re-fires, and redelivered
        rows drop at the watermarks (no double-apply)."""
        be = DeviceFleetBackend(
            capacity=128, max_batch=1 << 20, pump_mode=True,
            ring_depth=1, feed_deadline_ms=0.0,
        )
        _feed_backend(be, 0)
        pre_ft = _recovery_total("pump.feed", "fatal")
        faults.arm("pump.feed", faults.CrashAt("after"))
        with pytest.raises(faults.InjectedCrash):
            be.pump_feed_counted()
        faults.disarm()
        assert _recovery_total("pump.feed", "fatal") == pre_ft + 1
        _feed_backend(be, 0)  # at-least-once redelivery of the same round
        be.pump_feed_counted()
        be.pump_drain()
        assert be.stats()["ops_applied"] == N_CH * K  # no dup
        _pool_parity(be, self._reference(1))


# ---------------------------------------------------------------------------
# Residency chaos: the r19 doc.hibernate / doc.wake recovery matrix
# (docs/failure-semantics.md §"Residency lifecycle") — fail / crash-before /
# crash-after at both commit boundaries, bit-identical post-recovery state.


class TestResidencyChaos:
    def _reference(self, rounds: int) -> DeviceFleetBackend:
        ref = _make_backend()
        for r in range(rounds):
            _feed_backend(ref, r)
            ref.pump_stage()
        ref.pump_drain()
        return ref

    def _resident(self, rounds: int = 1) -> DeviceFleetBackend:
        be = _make_backend()
        for r in range(rounds):
            _feed_backend(be, r)
            be.pump_stage()
        be.pump_drain()
        return be

    def test_hibernate_fail_stays_resident_retry_succeeds(self):
        """``doc.hibernate`` fail → fallback: the doc stays RESIDENT
        with its slot live (counted, never silent), and a clean retry
        hibernates it for real."""
        from fluidframework_tpu.service import residency

        be = self._resident()
        idx = be._index[("d0", "s")]
        pre = _recovery_total("doc.hibernate", "fallback")
        faults.arm("doc.hibernate", faults.FailN(1))
        assert be.hibernate_doc("d0") is False
        faults.disarm()
        assert _recovery_total("doc.hibernate", "fallback") == pre + 1
        assert be.residency.state("d0") == residency.RESIDENT
        assert be.fleet.placement[idx] is not None, "slot must stay live"
        assert be.hibernate_doc("d0") is True  # clean retry
        assert be.residency.state("d0") == residency.COLD
        assert be.fleet.placement[idx] is None
        _feed_backend(be, 1)  # first op wakes it back
        be.pump_stage()
        be.pump_drain()
        stats = be.stats()
        assert stats["ops_applied"] == 2 * N_CH * K
        assert stats["docs_with_errors"] == 0
        _pool_parity(be, self._reference(2))

    def test_hibernate_crash_before_stays_resident(self):
        """Crash BEFORE the eviction commit: nothing happened — the doc
        is RESIDENT, the slot live, and the next round serves it as if
        the sweep never ran."""
        from fluidframework_tpu.service import residency

        be = self._resident()
        idx = be._index[("d0", "s")]
        faults.arm("doc.hibernate", faults.CrashAt("before"))
        with pytest.raises(faults.InjectedCrash):
            be.hibernate_doc("d0")
        faults.disarm()
        assert be.residency.state("d0") == residency.RESIDENT
        assert be.fleet.placement[idx] is not None
        _feed_backend(be, 1)
        be.pump_stage()
        be.pump_drain()
        assert be.stats()["ops_applied"] == 2 * N_CH * K
        _pool_parity(be, self._reference(2))

    def test_hibernate_crash_after_is_durably_cold_wake_serves(self):
        """Crash AFTER the eviction commit: the slots are freed and the
        cold records landed — the manager records the doc COLD (the
        at-least-once window resolved toward reality), and the first op
        wakes it through the normal path with bit-identical state."""
        from fluidframework_tpu.service import residency

        be = self._resident()
        idx = be._index[("d0", "s")]
        faults.arm("doc.hibernate", faults.CrashAt("after"))
        with pytest.raises(faults.InjectedCrash):
            be.hibernate_doc("d0")
        faults.disarm()
        assert be.residency.state("d0") == residency.COLD
        assert be.fleet.placement[idx] is None, "eviction was durable"
        _feed_backend(be, 1)
        be.pump_stage()
        be.pump_drain()
        stats = be.stats()
        assert stats["ops_applied"] == 2 * N_CH * K
        assert stats["docs_with_errors"] == 0
        assert be.residency.stats()["wakes"].get("ok", 0) == 1
        _pool_parity(be, self._reference(2))

    def test_wake_fail_parks_rows_flush_retries(self):
        """``doc.wake`` fail → retry: the durable/cold state is
        untouched and the triggering rows PARK (bounded queue — counted
        into pressure, never dropped); the quiescence flush re-attempts
        the wake and every parked row applies in order."""
        from fluidframework_tpu.service import residency

        be = self._resident()
        assert be.hibernate_doc("d0") is True
        pre = _recovery_total("doc.wake", "retry")
        faults.arm("doc.wake", faults.FailN(1))
        _feed_backend(be, 1)  # d0's frame parks; the rest buffer
        faults.disarm()
        assert _recovery_total("doc.wake", "retry") == pre + 1
        assert be.residency.state("d0") == residency.WAKING
        assert be.stats()["parked_rows"] == K
        assert be.needs_flush(), "parked rows must demand a flush"
        be.flush()  # the quiescence backstop retries the wake
        be.pump_drain()
        stats = be.stats()
        assert stats["ops_applied"] == 2 * N_CH * K
        assert stats["parked_rows"] == 0
        assert stats["docs_with_errors"] == 0
        assert be.residency.state("d0") == residency.RESIDENT
        _pool_parity(be, self._reference(2))

    def test_wake_crash_before_parks_rows_flush_recovers(self):
        """Crash BEFORE the restore: cold state untouched, rows parked;
        the disarmed flush retries the wake from the unchanged durable
        state — no op lost, none duplicated."""
        from fluidframework_tpu.service import residency

        be = self._resident()
        assert be.hibernate_doc("d0") is True
        faults.arm("doc.wake", faults.CrashAt("before"))
        ar = np.arange(K, dtype=np.int32)
        rows = np.zeros((K, OP_WIDTH), np.int32)
        rows[:, F_TYPE] = OP_INSERT
        rows[:, F_LEN] = 1
        rows[:, F_SEQ] = K + 1 + ar
        rows[:, F_REF] = K
        rows[:, F_ARG] = K + 1 + ar
        with pytest.raises(faults.InjectedCrash):
            be.enqueue_frame("d0", SeqFrame("s", 0, 1, rows, (), 0.0))
        faults.disarm()
        assert be.residency.state("d0") == residency.WAKING
        assert be.stats()["parked_rows"] == K
        for i in range(1, N_CH):  # the rest of the round feeds normally
            r2 = rows.copy()
            be.enqueue_frame(f"d{i}", SeqFrame("s", 0, 1, r2, (), 0.0))
        be.flush()
        be.pump_drain()
        stats = be.stats()
        assert stats["ops_applied"] == 2 * N_CH * K
        assert stats["parked_rows"] == 0
        assert be.residency.state("d0") == residency.RESIDENT
        _pool_parity(be, self._reference(2))

    def test_wake_crash_after_restore_is_idempotent(self):
        """Crash AFTER the restore: the slot is live and the rows
        unparked — the wake finishes as completed before the crash
        propagates, and the retry path (had one raced in) would find no
        cold record and count ``noop`` instead of double-restoring."""
        from fluidframework_tpu.service import residency

        be = self._resident()
        assert be.hibernate_doc("d0") is True
        idx = be._index[("d0", "s")]
        faults.arm("doc.wake", faults.CrashAt("after"))
        ar = np.arange(K, dtype=np.int32)
        rows = np.zeros((K, OP_WIDTH), np.int32)
        rows[:, F_TYPE] = OP_INSERT
        rows[:, F_LEN] = 1
        rows[:, F_SEQ] = K + 1 + ar
        rows[:, F_REF] = K
        rows[:, F_ARG] = K + 1 + ar
        with pytest.raises(faults.InjectedCrash):
            be.enqueue_frame("d0", SeqFrame("s", 0, 1, rows, (), 0.0))
        faults.disarm()
        assert be.residency.state("d0") == residency.RESIDENT
        assert be.fleet.placement[idx] is not None
        assert be.stats()["parked_rows"] == 0, "completed wake unparked"
        assert ("d0", "s") not in be._cold
        for i in range(1, N_CH):
            r2 = rows.copy()
            be.enqueue_frame(f"d{i}", SeqFrame("s", 0, 1, r2, (), 0.0))
        be.flush()
        be.pump_drain()
        assert be.stats()["ops_applied"] == 2 * N_CH * K
        _pool_parity(be, self._reference(2))


# ---------------------------------------------------------------------------
# Websocket delivery: requeue recovery over real sockets


class TestWsDeliveryChaos:
    def _converged(self, runtimes, text, timeout=10.0):
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for rt in runtimes:
                rt.process_incoming()
            if all(
                rt.get_channel("text").get_text() == text for rt in runtimes
            ):
                return True
            time.sleep(0.02)
        return False

    @pytest.mark.parametrize("kind", ["fail", "crash_before", "crash_after"])
    def test_delivery_failure_exactly_once(self, kind):
        """A failed delivery write requeues the unsent tail (watermarks
        only advance on success), a crash-after write does NOT requeue
        the op that reached the socket — either way every client sees
        each op exactly once."""
        from fluidframework_tpu.drivers.network_driver import (
            NetworkFluidService,
        )
        from fluidframework_tpu.models.shared_string import SharedString
        from fluidframework_tpu.runtime.container import ContainerRuntime
        from fluidframework_tpu.service.network_server import (
            FluidNetworkServer,
        )

        srv = FluidNetworkServer(service=PipelineFluidService(n_partitions=2))
        srv.start()
        try:
            a = ContainerRuntime(
                NetworkFluidService("127.0.0.1", srv.port), "wsdoc",
                channels=(SharedString("text"),),
            )
            b = ContainerRuntime(
                NetworkFluidService("127.0.0.1", srv.port), "wsdoc",
                channels=(SharedString("text"),),
            )
            assert self._converged([a, b], "")  # settle the handshakes
            pre = _recovery_total("ws.deliver")
            faults.arm("ws.deliver", _policy(kind))
            a.get_channel("text").insert_text(0, "hello")
            a.flush()
            assert self._converged([a, b], "hello"), (
                faults.stats(), kind,
            )
            assert faults.REGISTRY.injected_total("ws.deliver") == 1
            assert _recovery_total("ws.deliver") > pre
        finally:
            faults.disarm()
            srv.stop()


# ---------------------------------------------------------------------------
# Leases: coordination faults + the epoch-fence reroute


def _op(csn: int, ref: int) -> DocumentMessage:
    return DocumentMessage(
        client_sequence_number=csn,
        reference_sequence_number=ref,
        type=MessageType.OPERATION,
        contents=None,
    )


class TestLeaseChaos:
    @pytest.mark.parametrize("kind", ["fail", "crash_before", "crash_after"])
    def test_acquire_failure_retries_through_router(self, kind):
        """A coordination blip during acquire — including a crash AFTER
        the lease was written but before the caller saw the grant — is
        absorbed by the router's candidate sweep: the same node
        re-acquires its own lease on the retry pass."""
        svc = MultiNodeFluidService(n_nodes=3, rebalance_every=0)
        pre = _recovery_total("lease.acquire")
        faults.arm("lease.acquire", _policy(kind))
        conn = svc.connect("lease-doc")
        faults.disarm()
        assert faults.REGISTRY.injected_total("lease.acquire") == 1
        assert _recovery_total("lease.acquire") > pre
        conn.submit(_op(1, conn.join_seq))
        msgs = svc.get_deltas("lease-doc")
        assert [m.sequence_number for m in msgs] == [1, 2]

    def test_renew_failure_reowns_without_loss(self):
        svc = MultiNodeFluidService(n_nodes=3, rebalance_every=0)
        conn = svc.connect("renew-doc")
        conn.submit(_op(1, conn.join_seq))
        faults.arm("lease.renew", faults.FailN(1))
        conn.submit(_op(2, conn.join_seq))
        faults.disarm()
        assert faults.REGISTRY.injected_total("lease.renew") == 1
        seqs = [m.sequence_number for m in svc.get_deltas("renew-doc")]
        assert seqs == [1, 2, 3], "renew blip must not lose or dup ops"

    def test_lease_expiry_mid_flush_fenced_and_requeued(self, monkeypatch):
        """The epoch fence rejects a stale owner's mid-flight write and
        the service requeues the op with the NEW owner — sequenced
        exactly once, counted as {lease.renew,fence}."""
        t = [0.0]
        svc = MultiNodeFluidService(
            n_nodes=3, clock=lambda: t[0], lease_ttl_s=5.0,
            rebalance_every=0,
        )
        conn = svc.connect("fence-doc")
        conn.submit(_op(1, conn.join_seq))
        stale = next(
            n for n in svc.cluster.nodes if "fence-doc" in n._docs
        )
        # Lease lapses while the old owner still believes it owns the doc;
        # another node takes over (epoch bump fences the log).
        t[0] += 10.0
        other = next(n for n in svc.cluster.nodes if n is not stale)
        assert other.try_own("fence-doc")
        # The service races the stale owner once (the mid-flush window).
        orig_owner = svc.cluster.owner
        raced = []

        def racing_owner(doc_id):
            if not raced:
                raced.append(1)
                return stale
            return orig_owner(doc_id)

        monkeypatch.setattr(svc.cluster, "owner", racing_owner)
        pre = _recovery_total("lease.renew", "fence")
        conn.submit(_op(2, conn.join_seq))
        assert _recovery_total("lease.renew", "fence") == pre + 1
        seqs = [m.sequence_number for m in svc.get_deltas("fence-doc")]
        assert seqs == sorted(set(seqs)), "fenced op must not duplicate"
        ops = [
            m for m in svc.get_deltas("fence-doc")
            if m.type == MessageType.OPERATION
        ]
        assert [m.client_sequence_number for m in ops] == [1, 2]
        assert stale.op_rate.get("fence-doc") is None or (
            "fence-doc" not in stale._docs
        ), "stale owner must have forgotten the doc after the fence"


# ---------------------------------------------------------------------------
# The 93-writer cap: nack-at-cap + slot-expiry reuse through the pipeline


class TestWriterCap:
    def test_nack_at_cap_and_slot_reuse(self):
        """ROADMAP open item: MAX_WRITERS is enforced END TO END — writer
        94 gets a clean 429 nack through the full pipeline, and after a
        leave whose seq falls below the collab-window floor the freed
        slot readmits a new writer."""
        svc = PipelineFluidService(n_partitions=1, device_backend=False)
        conns = [svc.connect("cap-doc") for _ in range(MAX_WRITERS)]
        assert len({c.client_id for c in conns}) == MAX_WRITERS
        with pytest.raises(ConnectionError) as ei:
            svc.connect("cap-doc")
        assert "writer slots exhausted" in str(ei.value)
        # The nack is the sequencer's 429 LIMIT_EXCEEDED, delivered
        # through the broadcaster to the joining connection (pipeline
        # semantics, not just the DocumentSequencer unit contract).
        freed = conns[0]
        freed_slot = freed.client_id
        freed_conn_no = freed.conn_no
        freed.disconnect()
        # Before the floor advances past the leave, the cap still nacks:
        # the freed slot's stamps may still be inside a live collab
        # window.
        with pytest.raises(ConnectionError):
            svc.connect("cap-doc")
        # Every surviving writer submits against the current head; the
        # MSN floor advances past the leave seq and the slot recycles.
        for c in conns[1:]:
            c.submit(_op(1, svc.doc_head("cap-doc")))
        readmitted = svc.connect("cap-doc")
        assert readmitted.client_id == freed_slot
        assert readmitted.conn_no > freed_conn_no, (
            "recycled slot must carry a fresh never-recycled ordinal"
        )
        # And the readmitted writer can sequence ops.
        readmitted.submit(_op(1, svc.doc_head("cap-doc")))
        head = svc.doc_head("cap-doc")
        assert svc.get_deltas("cap-doc")[-1].sequence_number == head
