"""Datastore routing, handles, and garbage collection.

Reference: packages/runtime/datastore (two-level op routing),
core-interfaces IFluidHandle, packages/runtime/garbage-collector +
container-runtime GC (D.3): mark-phase reachability from root/aliased
objects over stored handles, unreferenced state machine
Inactive -> TombstoneReady -> SweepReady, gc tree in the summary.
"""

import pytest

from fluidframework_tpu.models.shared_map import SharedMap
from fluidframework_tpu.models.shared_string import SharedString
from fluidframework_tpu.runtime.container import ContainerRuntime, TombstoneError
from fluidframework_tpu.runtime.datastore import FluidDataStore
from fluidframework_tpu.runtime.gc import (
    GCOptions,
    GarbageCollector,
    UnreferencedState,
    run_garbage_collection,
)
from fluidframework_tpu.runtime.handles import (
    collect_handle_routes,
    encode_handle,
    is_handle,
)
from fluidframework_tpu.service.local_server import LocalFluidService


def drain(rts):
    for rt in rts:
        rt.flush()
    while any(rt.process_incoming() for rt in rts):
        pass


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now


class TestReachability:
    def test_basic_graph(self):
        graph = {"/a": ["/b"], "/b": ["/c"], "/d": []}
        assert run_garbage_collection(graph, ["/a"]) == {"/a", "/b", "/c"}

    def test_cycle_terminates(self):
        graph = {"/a": ["/b"], "/b": ["/a"]}
        assert run_garbage_collection(graph, ["/a"]) == {"/a", "/b"}

    def test_handle_walk(self):
        v = {"x": [1, {"h": encode_handle("/ds/chan")}], "y": encode_handle("/m")}
        assert sorted(collect_handle_routes(v)) == ["/ds/chan", "/m"]
        assert is_handle(encode_handle("/a"))
        assert not is_handle({"type": "other"})


class TestDataStoreRouting:
    def test_nested_ops_converge(self):
        svc = LocalFluidService()
        mk = lambda: ContainerRuntime(
            svc,
            "doc",
            channels=(
                FluidDataStore("ds", channels=(SharedMap("m"), SharedString("s"))),
            ),
        )
        a, b = mk(), mk()
        dsa = a.get_channel("ds")
        dsb = b.get_channel("ds")
        dsa.get_channel("m").set("k", 7)
        dsa.get_channel("s").insert_text(0, "hi")
        dsb.get_channel("m").set("j", 8)
        drain([a, b])
        assert dsb.get_channel("m").get("k") == 7
        assert dsa.get_channel("m").get("j") == 8
        assert dsb.get_channel("s").get_text() == "hi"

    def test_nested_summary_roundtrip(self):
        svc = LocalFluidService()
        a = ContainerRuntime(
            svc, "doc", channels=(FluidDataStore("ds", channels=(SharedMap("m"),)),)
        )
        a.get_channel("ds").get_channel("m").set("k", 1)
        drain([a])
        handle = a.submit_summary()
        drain([a])
        summary = svc.store.get_summary(handle)
        b = ContainerRuntime(
            svc, "doc", channels=(FluidDataStore("ds", channels=(SharedMap("m"),)),)
        )
        assert b.get_channel("ds").get_channel("m").get("k") == 1

    def test_nested_reconnect_resubmit(self):
        svc = LocalFluidService()
        mk = lambda: ContainerRuntime(
            svc, "doc", channels=(FluidDataStore("ds", channels=(SharedMap("m"),)),)
        )
        a, b = mk(), mk()
        a.disconnect()
        a.get_channel("ds").get_channel("m").set("offline", 1)
        a.flush()
        a.reconnect()
        drain([a, b])
        assert b.get_channel("ds").get_channel("m").get("offline") == 1


class TestGC:
    def make(self, clock):
        svc = LocalFluidService()
        opts = GCOptions(
            inactive_timeout_s=100,
            tombstone_timeout_s=1000,
            sweep_grace_s=100,
            sweep_enabled=True,
            clock=clock,
        )
        rt = ContainerRuntime(svc, "doc", channels=(SharedMap("root"),), gc_options=opts)
        rt.create_channel(SharedMap("loose"), root=False)
        return svc, rt

    def test_referenced_stays_active(self):
        clock = FakeClock()
        svc, rt = self.make(clock)
        rt.get_channel("root").set("ref", rt.handle_for("loose"))
        drain([rt])
        res = rt.run_gc()
        assert "/loose" in res.reachable
        assert res.unreferenced == {}

    def test_unreferenced_progression(self):
        clock = FakeClock()
        svc, rt = self.make(clock)
        res = rt.run_gc()  # never referenced at all
        assert res.unreferenced["/loose"] is UnreferencedState.ACTIVE
        clock.now += 150
        assert rt.run_gc().unreferenced["/loose"] is UnreferencedState.INACTIVE
        clock.now += 900
        assert (
            rt.run_gc().unreferenced["/loose"] is UnreferencedState.TOMBSTONE_READY
        )
        with pytest.raises(TombstoneError):
            rt.get_channel("loose")

    def test_revival_resets_tracking(self):
        clock = FakeClock()
        svc, rt = self.make(clock)
        rt.run_gc()
        clock.now += 150
        assert rt.run_gc().unreferenced["/loose"] is UnreferencedState.INACTIVE
        rt.get_channel("root").set("ref", rt.handle_for("loose"))
        drain([rt])
        res = rt.run_gc()
        assert "/loose" in res.reachable and res.unreferenced == {}
        # Dropping the reference restarts the clock from now.
        rt.get_channel("root").delete("ref")
        drain([rt])
        assert rt.run_gc().unreferenced["/loose"] is UnreferencedState.ACTIVE

    def test_sweep_excludes_from_summary(self):
        clock = FakeClock()
        svc, rt = self.make(clock)
        rt.run_gc()
        clock.now += 2000  # past tombstone + grace
        summary = rt.summarize()
        assert "loose" not in summary["channels"]
        assert "root" in summary["channels"]

    def test_gc_state_rides_summary(self):
        clock = FakeClock()
        svc, rt = self.make(clock)
        rt.run_gc()
        clock.now += 150
        drain([rt])
        rt.submit_summary()
        drain([rt])
        opts = GCOptions(
            inactive_timeout_s=100,
            tombstone_timeout_s=1000,
            sweep_grace_s=100,
            clock=clock,
        )
        b = ContainerRuntime(
            svc, "doc", channels=(SharedMap("root"), SharedMap("loose")),
            gc_options=opts,
        )
        # The loaded client adopts the summarizer's unreferenced timestamps.
        assert b.gc.unreferenced_since.get("/loose") == 1000.0
        assert b.gc.state_of("/loose") is UnreferencedState.INACTIVE

    def test_datastore_children_traced(self):
        svc = LocalFluidService()
        clock = FakeClock()
        opts = GCOptions(inactive_timeout_s=100, clock=clock)
        rt = ContainerRuntime(
            svc,
            "doc",
            channels=(
                FluidDataStore("ds", channels=(SharedMap("m"),)),
                SharedMap("root"),
            ),
            gc_options=opts,
        )
        rt.get_channel("ds").get_channel("m").set(
            "x", rt.handle_for("ds2", "inner")
        )
        rt.create_channel(
            FluidDataStore("ds2", channels=(SharedMap("inner"),)), root=False
        )
        drain([rt])
        res = rt.run_gc()
        # ds2's child is referenced through the handle in ds/m.
        assert "/ds2/inner" in res.reachable


class TestReviewRegressions:
    def test_quorum_mode_survives_summary_load(self):
        svc = LocalFluidService()
        r = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),), mode="read")
        w = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
        drain([r, w])
        w.get_channel("m").set("k", 1)
        drain([r, w])
        w.submit_summary()
        drain([r, w])
        c = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
        # The loaded replica must agree the read client is ineligible.
        assert c.quorum_members[r.client_id]["mode"] == "read"
        assert not any(
            cid == r.client_id
            for cid, d in c.quorum_members.items()
            if d["mode"] == "write"
        )

    def test_referenced_child_keeps_datastore_alive(self):
        clock = FakeClock()
        svc = LocalFluidService()
        opts = GCOptions(
            inactive_timeout_s=10, tombstone_timeout_s=20, sweep_grace_s=10,
            sweep_enabled=True, clock=clock,
        )
        rt = ContainerRuntime(
            svc, "doc", channels=(SharedMap("root"),), gc_options=opts
        )
        rt.create_channel(
            FluidDataStore("ds2", channels=(SharedMap("inner"),)), root=False
        )
        rt.get_channel("root").set("x", rt.handle_for("ds2", "inner"))
        drain([rt])
        res = rt.run_gc()
        assert "/ds2/inner" in res.reachable and "/ds2" in res.reachable
        clock.now += 100
        summary = rt.summarize()
        assert "ds2" in summary["channels"]  # never swept while child is live

    def test_swept_route_stays_dead(self):
        clock = FakeClock()
        svc = LocalFluidService()
        opts = GCOptions(
            inactive_timeout_s=10, tombstone_timeout_s=20, sweep_grace_s=10,
            sweep_enabled=True, tombstone_mode=True, clock=clock,
        )
        rt = ContainerRuntime(
            svc, "doc", channels=(SharedMap("root"),), gc_options=opts
        )
        rt.create_channel(SharedMap("loose"), root=False)
        rt.run_gc()
        clock.now += 100
        res = rt.run_gc()
        assert "/loose" in res.swept
        with pytest.raises(TombstoneError):
            rt.get_channel("loose")
        # ...and across a summary round trip.
        state = rt.gc.summarize()
        fresh = GarbageCollector(opts)
        fresh.load(state)
        assert fresh.is_tombstoned("/loose")


class TestNackCseqRecovery:
    def test_propose_consumes_cseq_before_nack(self):
        """PROPOSE/NOOP consume server-side clientSequenceNumbers; nack
        recovery must resume above them, not reuse them (sequencer dedup
        would silently drop the resubmission)."""
        svc = LocalFluidService()
        a = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
        b = ContainerRuntime(svc, "doc", channels=(SharedMap("m"),))
        a.get_channel("m").set("k0", 0)
        drain([a, b])
        a.propose("code", "v2")
        drain([a, b])
        # Force a nack: artificially regress refSeq below the MSN by
        # letting b advance the window far ahead while a sits behind.
        for i in range(5):
            b.get_channel("m").set(f"b{i}", i)
            b.flush()
        b.send_noop()
        b.process_incoming()
        # a submits with a stale refSeq -> sequencer nacks -> recovery path.
        a.get_channel("m").set("k1", 1)
        a.flush()
        drain([a, b])
        assert a.get_channel("m").get("k1") == 1
        assert b.get_channel("m").get("k1") == 1
        assert not a.pending
