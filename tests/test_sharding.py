"""Mesh sharding tests on the virtual 8-device CPU mesh."""

import jax
import numpy as np

from fluidframework_tpu.ops import encode as E
from fluidframework_tpu.ops.merge_kernel import jit_apply_ops
from fluidframework_tpu.ops.segment_state import (
    SegmentState,
    make_state,
    materialize,
)
from fluidframework_tpu.parallel.mesh import DocShard, make_mesh
from fluidframework_tpu.protocol.constants import NO_CLIENT, OP_WIDTH


def test_virtual_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def make_ops(n_docs, rows):
    batch = np.stack(rows).astype(np.int32)
    return np.broadcast_to(batch, (n_docs,) + batch.shape).copy()


def test_docshard_apply_matches_single_doc():
    pay = {1: "hello", 2: "XY"}
    rows = [
        E.insert(0, 1, 5, seq=1, ref=0, client=0),
        E.insert(2, 2, 2, seq=2, ref=1, client=1),
        E.remove(1, 4, seq=3, ref=2, client=0),
    ]
    shard = DocShard(n_docs=32, capacity=64)
    stats = shard.apply(make_ops(32, rows))
    assert int(stats["docs_with_errors"]) == 0
    assert int(stats["max_seq"]) == 3

    single = jit_apply_ops(make_state(64, NO_CLIENT), np.stack(rows).astype(np.int32))
    expect = materialize(single, pay)

    host = SegmentState(*[np.asarray(x) for x in shard.state])
    for d in (0, 7, 31):
        doc = SegmentState(*[x[d] for x in host])
        assert materialize(doc, pay) == expect


def test_docshard_heterogeneous_ops():
    pay = {1: "aaaa", 2: "bb"}
    shard = DocShard(n_docs=8, capacity=32)
    ops = np.zeros((8, 2, OP_WIDTH), np.int32)
    for d in range(8):
        ops[d, 0] = E.insert(0, 1, 4, seq=1, ref=0, client=0)
        if d % 2:
            ops[d, 1] = E.insert(d % 4, 2, 2, seq=2, ref=1, client=1)
        else:
            ops[d, 1] = E.remove(0, 2, seq=2, ref=1, client=1)
    shard.apply(ops)
    host = SegmentState(*[np.asarray(x) for x in shard.state])
    texts = [
        materialize(SegmentState(*[x[d] for x in host]), pay) for d in range(8)
    ]
    assert texts[0] == "aa" and texts[1] == "abbaaa"
    assert texts[2] == "aa" and texts[3] == "aaabba"


def test_docshard_compact_stable():
    pay = {1: "abcdef"}
    shard = DocShard(n_docs=8, capacity=32)
    rows = [
        E.insert(0, 1, 6, seq=1, ref=0, client=0),
        E.remove(1, 3, seq=2, ref=1, client=0, msn=2),
    ]
    shard.apply(make_ops(8, rows))
    before = SegmentState(*[np.asarray(x) for x in shard.state])
    shard.compact()
    after = SegmentState(*[np.asarray(x) for x in shard.state])
    for d in range(8):
        t0 = materialize(SegmentState(*[x[d] for x in before]), pay)
        t1 = materialize(SegmentState(*[x[d] for x in after]), pay)
        assert t0 == t1 == "adef"


def test_mesh_uses_all_devices():
    mesh = make_mesh()
    assert mesh.devices.size == 8
    shard = DocShard(n_docs=16, capacity=16, mesh=mesh)
    # The doc axis must actually be distributed across devices.
    lane = shard.state.kind
    assert len(lane.sharding.device_set) == 8

def test_pallas_backend_matches_xla_on_mesh():
    """DocShard's Pallas backend under shard_map is bit-identical to the
    XLA backend across an 8-device mesh (stats and every lane)."""
    import numpy as np

    from fluidframework_tpu.ops.segment_state import SEGMENT_LANES
    from fluidframework_tpu.parallel.mesh import DocShard, make_mesh

    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from __graft_entry__ import _example_ops

    mesh = make_mesh(8)
    a = DocShard(n_docs=32, capacity=128, mesh=mesh, backend="xla")
    b = DocShard(n_docs=32, capacity=128, mesh=mesh, backend="pallas")
    ops = _example_ops(32, 8)
    sa, sb = a.apply(ops), b.apply(ops)
    assert {k: int(v) for k, v in sa.items()} == {
        k: int(v) for k, v in sb.items()
    }
    a.compact()
    b.compact()
    ub = b.unpacked_state()
    for k in SEGMENT_LANES + ("count", "min_seq", "cur_seq", "err"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.state, k)), np.asarray(getattr(ub, k)),
            err_msg=k,
        )


def test_docshard_step_functions_shared_across_instances():
    """Recompile regression (graftlint recompile-hazard): DocShard built
    its jitted step per instance, so every new shard of the same
    deployment shape re-traced an identical program. The builders are now
    module-level/cached — two same-shape shards must share the SAME
    compiled callables."""
    from fluidframework_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()
    a = DocShard(n_docs=16, capacity=16, mesh=mesh, backend="xla")
    b = DocShard(n_docs=16, capacity=16, mesh=mesh, backend="xla")
    assert a._step is b._step
    p = DocShard(n_docs=16, capacity=16, mesh=mesh, backend="pallas")
    q = DocShard(n_docs=16, capacity=16, mesh=mesh, backend="pallas")
    assert p._pallas_step is q._pallas_step
    assert p._pallas_compact is q._pallas_compact
