"""Multi-replica convergence fuzz ("farm" tests).

The TPU analog of the reference's merge-tree farm suites
(``client.conflictFarm.spec.ts``): N clients generate random local ops
against their own replica state (kernel + oracle), a FIFO sequencer assigns
the total order, every replica (clients + a server replica) applies the
sequenced stream — including local-echo acks — and all replicas must end
bit-identical. This is the race-detector equivalent for merge logic
(SURVEY.md §5.2: determinism checking).
"""

import numpy as np
import pytest

from fluidframework_tpu.ops import encode as E
from fluidframework_tpu.ops.merge_kernel import compact, jit_apply_ops
from fluidframework_tpu.ops.segment_state import (
    make_state,
    materialize,
    to_host,
)
from fluidframework_tpu.protocol.constants import (
    F_CLIENT,
    F_LSEQ,
    F_SEQ,
    F_TYPE,
    KIND_FREE,
    NO_CLIENT,
    OP_ANNOTATE,
    OP_INSERT,
    OP_REMOVE,
    RSEQ_NONE,
    UNASSIGNED_SEQ,
)
from fluidframework_tpu.testing.oracle import OracleDoc

CAP = 512
ALPHABET = "abcdefghijklmnopqrstuvwxyz"
OP_KIND = {OP_INSERT: "insert", OP_REMOVE: "remove", OP_ANNOTATE: "annotate"}


class Replica:
    """One client: kernel state + oracle mirror + inbox + pending queue."""

    def __init__(self, client: int):
        self.client = client
        self.state = make_state(CAP, client)
        self.oracle = OracleDoc(client)
        self.inbox = []
        self.ref_seq = 0
        self.lseq = 0

    def _apply(self, row: np.ndarray):
        self.state = jit_apply_ops(self.state, row[None, :].astype(np.int32))
        self.oracle.apply(row)

    def submit(self, row: np.ndarray) -> tuple:
        """Apply a local (unacked) op and return the submission record."""
        self.lseq += 1
        row = row.copy()
        row[F_LSEQ] = self.lseq
        self._apply(row)
        return (self.client, row)

    def deliver(self, seq: int, sender: int, row: np.ndarray):
        if sender == self.client:
            kind = OP_KIND[int(row[F_TYPE])]
            self._apply(E.ack(kind, int(row[F_LSEQ]), seq))
        else:
            srow = row.copy()
            srow[F_SEQ] = seq
            srow[F_LSEQ] = 0
            self._apply(srow)
        self.ref_seq = seq

    def text(self, payloads):
        return materialize(self.state, payloads)


def visible_struct(state):
    """Structural fingerprint of the *visible* document.

    Tombstone relative order may legitimately differ between replicas (the
    reference has the same property: a local insert tie-breaks in front of an
    acked tombstone that remote replicas skip entirely), so convergence is
    asserted on visible rows only.
    """
    h = to_host(state)
    rows = []
    for i in range(int(h.count)):
        if int(h.kind[i]) == KIND_FREE or int(h.rseq[i]) != RSEQ_NONE:
            continue
        rows.append(
            (
                int(h.orig[i]),
                int(h.off[i]),
                int(h.length[i]),
                int(h.seq[i]),
                int(h.client[i]),
                int(h.aval[i]),
            )
        )
    return rows


def gen_local_op(rng, rep: Replica, payloads, next_orig):
    length = len(rep.oracle.text(payloads))
    choice = rng.integers(0, 3) if length > 0 else 0
    if choice == 0:
        n = int(rng.integers(1, 5))
        payloads[next_orig[0]] = "".join(rng.choice(list(ALPHABET), n))
        row = E.insert(
            int(rng.integers(0, length + 1)),
            next_orig[0],
            n,
            seq=UNASSIGNED_SEQ,
            ref=rep.ref_seq,
            client=rep.client,
        )
        next_orig[0] += 1
    elif choice == 1:
        a = int(rng.integers(0, length))
        b = int(rng.integers(a + 1, min(length, a + 8) + 1))
        row = E.remove(a, b, seq=UNASSIGNED_SEQ, ref=rep.ref_seq, client=rep.client)
    else:
        a = int(rng.integers(0, length))
        b = int(rng.integers(a + 1, min(length, a + 8) + 1))
        row = E.annotate(
            a, b, int(rng.integers(1, 50)), seq=UNASSIGNED_SEQ,
            ref=rep.ref_seq, client=rep.client,
        )
    return row


@pytest.mark.parametrize("seed", range(10))
def test_farm_convergence(seed):
    rng = np.random.default_rng(seed)
    n_clients = 3 + seed % 3
    n_ops = 30
    reps = [Replica(c) for c in range(n_clients)]
    server_k = make_state(CAP, NO_CLIENT)
    server_o = OracleDoc(NO_CLIENT)
    payloads = {}
    next_orig = [1]

    raw_queue = []  # FIFO into the "sequencer"
    seq = 0
    sequenced = []  # (seq, sender, row)
    submitted = 0

    def sequence_some(k):
        nonlocal seq, server_k, raw_queue
        for _ in range(min(k, len(raw_queue))):
            sender, row = raw_queue.pop(0)
            seq += 1
            srow = row.copy()
            srow[F_SEQ] = seq
            srow[F_LSEQ] = 0
            server_k = jit_apply_ops(server_k, srow[None, :].astype(np.int32))
            server_o.apply(srow)
            sequenced.append((seq, sender, row))

    while submitted < n_ops * n_clients:
        act = rng.integers(0, 3)
        c = int(rng.integers(0, n_clients))
        rep = reps[c]
        if act == 0:
            raw_queue.append(rep.submit(gen_local_op(rng, rep, payloads, next_orig)))
            submitted += 1
        elif act == 1:
            sequence_some(int(rng.integers(1, 4)))
        else:
            # Deliver some sequenced ops to a random client, in order.
            delivered = [s for s, _, _ in sequenced if s <= rep.ref_seq]
            pending = sequenced[len(delivered):]
            for s, sender, row in pending[: int(rng.integers(1, 5))]:
                rep.deliver(s, sender, row)

    # Drain: sequence and deliver everything.
    sequence_some(len(raw_queue))
    for rep in reps:
        for s, sender, row in sequenced:
            if s > rep.ref_seq:
                rep.deliver(s, sender, row)

    texts = [rep.text(payloads) for rep in reps]
    server_text = materialize(server_k, payloads)
    assert all(t == texts[0] for t in texts), f"client texts diverged: {texts}"
    assert server_text == texts[0]
    assert server_o.text(payloads) == texts[0]

    structs = [visible_struct(rep.state) for rep in reps]
    structs.append(visible_struct(server_k))
    assert all(s == structs[0] for s in structs), "replica structures diverged"

    for rep in reps:
        assert int(to_host(rep.state).err) == 0

    # Advance the collab window to the final seq and compact every replica:
    # text must be stable and still convergent.
    fin = np.stack([E.noop(msn=seq, seq=seq)]).astype(np.int32)
    compacted = []
    for rep in reps:
        st = compact(jit_apply_ops(rep.state, fin))
        compacted.append(materialize(st, payloads))
    assert all(t == texts[0] for t in compacted)
