"""Native batch ticket loop vs the Python DocumentSequencer (deli parity)."""

import numpy as np
import pytest

from fluidframework_tpu.protocol.types import DocumentMessage, MessageType
from fluidframework_tpu.service.fleet_sequencer import FleetSequencer
from fluidframework_tpu.service.sequencer import DocumentSequencer


def _py_reference(n_docs, streams):
    """Ticket the same streams through per-doc Python sequencers."""
    seqs = []
    for d in range(n_docs):
        s = DocumentSequencer(f"d{d}")
        client = s.join().contents["clientId"]
        got = []
        for _client, cseq, ref in streams[d]:
            m = s.ticket(
                client,
                DocumentMessage(
                    client_sequence_number=int(cseq),
                    reference_sequence_number=int(ref),
                    type=MessageType.OPERATION,
                ),
            )
            got.append(
                (0, 0)
                if m is None
                else (m.sequence_number, m.minimum_sequence_number)
            )
        seqs.append(got)
    return seqs


@pytest.mark.parametrize("seed", range(5))
def test_parity_with_python_sequencer(seed):
    rng = np.random.default_rng(seed)
    n_docs, k = 8, 40
    fs = FleetSequencer(n_docs, max_writers=4)
    joins = fs.join_all(slot=0)
    streams = np.zeros((n_docs, k, 3), np.int32)
    for d in range(n_docs):
        cseq = 0
        for i in range(k):
            dup = cseq > 0 and rng.random() < 0.1
            if not dup:
                cseq += 1
            # ref tracks the latest seq the client saw (joins consume 1).
            streams[d, i] = (0, cseq, joins[d] + i // 2)
    out, err = fs.ticket_batch(streams)
    assert not err.any()
    want = _py_reference(n_docs, streams)
    for d in range(n_docs):
        # Duplicates are dropped on both paths; their msn placeholder is
        # not part of the observable stream — normalize to (0, 0).
        got = [(int(a), int(b) if a else 0) for a, b in out[d]]
        assert got == want[d], f"doc {d}"


def test_gap_and_stale_flag_slow_path():
    fs = FleetSequencer(2, max_writers=2)
    joins = fs.join_all(slot=0)
    ops = np.zeros((2, 2, 3), np.int32)
    ops[0, 0] = (0, 2, joins[0])  # gap: cseq jumps to 2
    ops[1, 0] = (0, 1, 0)  # stale: ref below the client's join floor
    out, err = fs.ticket_batch(ops)
    assert err[0] == 1 and err[1] == 2


def test_unknown_client_flags():
    fs = FleetSequencer(1, max_writers=2)
    fs.join_all(slot=0)
    ops = np.zeros((1, 1, 3), np.int32)
    ops[0, 0] = (1, 1, 1)  # slot 1 never joined
    _out, err = fs.ticket_batch(ops)
    assert err[0] == 3


def test_native_and_python_paths_agree():
    rng = np.random.default_rng(7)
    n_docs, k = 4, 30
    streams = np.zeros((n_docs, k, 3), np.int32)
    a = FleetSequencer(n_docs, max_writers=4)
    b = FleetSequencer(n_docs, max_writers=4)
    ja = a.join_all(slot=0)
    b.join_all(slot=0)
    for d in range(n_docs):
        for i in range(k):
            streams[d, i] = (0, i + 1, ja[d] + i)
    out_a, err_a = a.ticket_batch(streams)
    if not a.native_available:
        pytest.skip("native ticket loop unavailable")
    b._native = type("X", (), {"available": False})()  # force Python path
    out_b, err_b = b.ticket_batch(streams)
    assert (out_a == out_b).all() and (err_a == err_b).all()
    assert (a.doc_state == b.doc_state).all()
    assert (a.clients == b.clients).all()
