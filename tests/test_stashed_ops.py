"""Stashed-op close + rehydrate (reference pendingStateManager.ts:205
applyStashedOpsAt, containerRuntime.ts:3248 getPendingLocalState): unacked
local state serializes, the process closes, and a LATER session resumes it
— converging with everything that happened in between."""

import json

import pytest

from fluidframework_tpu.models.shared_map import SharedMap
from fluidframework_tpu.models.shared_string import SharedString
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.local_server import LocalFluidService


def drain(rts):
    busy = True
    while busy:
        busy = any(rt.process_incoming() for rt in rts if rt.connected)


def channels():
    return (SharedString("text"), SharedMap("map"))


def test_offline_close_rehydrate_converges():
    # VERDICT r1 #7 "Done": edit offline, close, rehydrate in a fresh
    # runtime, converge with concurrent remote edits.
    svc = LocalFluidService()
    a = ContainerRuntime(svc, "doc", channels=channels())
    b = ContainerRuntime(svc, "doc", channels=channels())
    a.get_channel("text").insert_text(0, "hello world")
    drain([a, b])

    a.disconnect()
    a.get_channel("text").insert_text(5, "!")  # offline edits
    a.get_channel("map").set("who", "a")
    stash = json.loads(json.dumps(a.get_pending_local_state()))  # wire-safe
    del a  # the process is gone

    b.get_channel("text").insert_text(0, ">> ")  # concurrent remote edit
    drain([b])

    a2 = ContainerRuntime.rehydrate(svc, "doc", stash, channels=channels())
    drain([a2, b])
    assert (
        a2.get_channel("text").get_text()
        == b.get_channel("text").get_text()
        == ">> hello! world"
    )
    assert b.get_channel("map").get("who") == "a"


def test_stash_preserves_optimistic_view():
    svc = LocalFluidService()
    a = ContainerRuntime(svc, "doc", channels=channels())
    a.get_channel("text").insert_text(0, "base")
    drain([a])
    a.disconnect()
    a.get_channel("text").insert_text(4, "+more")
    stash = json.loads(json.dumps(a.get_pending_local_state()))
    a2 = ContainerRuntime.rehydrate(svc, "doc", stash, channels=channels())
    # The rehydrated session sees its own unacked edit immediately.
    assert a2.get_channel("text").get_text() == "base+more"
    drain([a2])
    assert a2.get_channel("text").get_text() == "base+more"


def test_stash_with_inflight_pending_ops():
    # Ops submitted-but-unacked (pending FIFO) also stash: the service
    # sequenced them, so the rehydrated session must NOT duplicate them...
    # unless they never sequenced — here the wire swallowed them, so the
    # stash replays them exactly once.
    svc = LocalFluidService()
    a = ContainerRuntime(svc, "doc", channels=channels())
    b = ContainerRuntime(svc, "doc", channels=channels())
    a.get_channel("text").insert_text(0, "base")
    drain([a, b])
    a.connection.submit = lambda msg: None  # dying socket swallows
    a.get_channel("text").insert_text(4, "?")
    a.flush()
    assert a.pending
    stash = json.loads(json.dumps(a.get_pending_local_state()))
    old_id = a.client_id
    del a
    svc.disconnect("doc", old_id)  # server notices the death
    b.get_channel("text").insert_text(0, "[")
    drain([b])
    a2 = ContainerRuntime.rehydrate(svc, "doc", stash, channels=channels())
    drain([a2, b])
    assert (
        a2.get_channel("text").get_text()
        == b.get_channel("text").get_text()
        == "[base?"
    )


def test_stash_pending_blob_rehydrates():
    svc = LocalFluidService()
    a = ContainerRuntime(svc, "doc", channels=channels())
    drain([a])
    a.disconnect()
    handle = a.upload_blob(b"stashed-bytes")  # offline: bytes ride the stash
    a.get_channel("map").set("blob", handle)
    stash = json.loads(json.dumps(a.get_pending_local_state()))
    a2 = ContainerRuntime.rehydrate(svc, "doc", stash, channels=channels())
    drain([a2])
    b = ContainerRuntime(svc, "doc", channels=channels())
    assert b.get_blob(b.get_channel("map").get("blob")) == b"stashed-bytes"


def test_stash_pending_remove_restamps_client_slot():
    # A pending REMOVE's removers bit must move from the closed session's
    # slot to the rehydrated one, or a future holder of the old slot would
    # see phantom removals.
    svc = LocalFluidService()
    a = ContainerRuntime(svc, "doc", channels=channels())
    b = ContainerRuntime(svc, "doc", channels=channels())
    a.get_channel("text").insert_text(0, "abcdef")
    drain([a, b])
    a.disconnect()
    a.get_channel("text").remove_range(2, 4)  # pending remove rides stash
    stash = json.loads(json.dumps(a.get_pending_local_state()))
    del a
    b.get_channel("text").insert_text(0, "XY")
    drain([b])
    a2 = ContainerRuntime.rehydrate(svc, "doc", stash, channels=channels())
    drain([a2, b])
    assert (
        a2.get_channel("text").get_text()
        == b.get_channel("text").get_text()
        == "XYabef"
    )


def test_stash_sequenced_inflight_op_not_duplicated():
    # The critical dual of the swallowed case: the op DID sequence before
    # the close. Catch-up must ack it via the stashed generation (not apply
    # it as remote on top of the optimistic rows, not resubmit it again).
    svc = LocalFluidService()
    a = ContainerRuntime(svc, "doc", channels=channels())
    b = ContainerRuntime(svc, "doc", channels=channels())
    a.get_channel("text").insert_text(0, "base")
    drain([a, b])
    a.get_channel("text").insert_text(4, "!")
    a.flush()  # sequenced server-side; echo never processed
    assert a.pending
    stash = json.loads(json.dumps(a.get_pending_local_state()))
    old_id = a.client_id
    del a
    svc.disconnect("doc", old_id)
    b.get_channel("text").insert_text(0, "[")
    drain([b])
    a2 = ContainerRuntime.rehydrate(svc, "doc", stash, channels=channels())
    drain([a2, b])
    assert (
        a2.get_channel("text").get_text()
        == b.get_channel("text").get_text()
        == "[base!"
    )


def test_stash_preserves_sequenced_container_state():
    # Blob bindings, approved proposals, and quorum-derived state at the
    # stash point must survive rehydration (the stash replaces the summary
    # load, so it must carry everything a summary would).
    svc = LocalFluidService()
    a = ContainerRuntime(svc, "doc", channels=channels())
    b = ContainerRuntime(svc, "doc", channels=channels())
    handle = a.upload_blob(b"bound-bytes")
    a.get_channel("map").set("blob", handle)
    a.propose("code", "v9")
    drain([a, b])
    for rt in (a, b):
        rt.send_noop()
    drain([a, b])
    assert a.approved_proposals.get("code") == "v9"
    stash = json.loads(json.dumps(a.get_pending_local_state()))
    del a
    a2 = ContainerRuntime.rehydrate(svc, "doc", stash, channels=channels())
    drain([a2, b])
    assert a2.get_blob(a2.get_channel("map").get("blob")) == b"bound-bytes"
    assert a2.approved_proposals.get("code") == "v9"
    assert set(a2.quorum_members) >= {b.client_id}


def test_stash_sequenced_proposal_not_reproposed():
    # A proposal sequenced before the close must not be blindly re-proposed
    # by the rehydrated session (it would overwrite newer values).
    svc = LocalFluidService()
    a = ContainerRuntime(svc, "doc", channels=channels())
    b = ContainerRuntime(svc, "doc", channels=channels())
    drain([a, b])
    a.propose("key", "old")  # sequenced; echo unseen
    stash = json.loads(json.dumps(a.get_pending_local_state()))
    old_id = a.client_id
    del a
    svc.disconnect("doc", old_id)
    b.process_incoming()
    b.propose("key", "new")  # later value
    drain([b])
    a2 = ContainerRuntime.rehydrate(svc, "doc", stash, channels=channels())
    drain([a2, b])
    for rt in (a2, b):
        rt.send_noop()
    drain([a2, b])
    # "new" sequenced after "old"; a blind re-propose of "old" by a2 would
    # have sequenced after "new" and won. It must not.
    assert a2.approved_proposals.get("key") == "new"
    assert b.approved_proposals.get("key") == "new"
