"""The bench-artifact CI gate (tools/check_bench_artifact.py): committed
round artifacts after r5 must carry the serving-path headline metrics."""

import json
import os
import sys


def _tool():
    tools = os.path.join(os.path.dirname(__file__), "..", "tools")
    sys.path.insert(0, tools)
    try:
        import check_bench_artifact
    finally:
        sys.path.remove(tools)
    return check_bench_artifact


def _write(tmp_path, name, tail_lines):
    (tmp_path / name).write_text(
        json.dumps({"n": 1, "rc": 0, "tail": "\n".join(tail_lines)})
    )


def test_missing_serving_metrics_fails(tmp_path):
    cba = _tool()
    _write(tmp_path, "BENCH_r06.json",
           ['{"metric": "merge_ops_per_sec_per_chip", "value": 1}'])
    assert cba.check(str(tmp_path)) == 1


def test_complete_artifact_passes(tmp_path):
    cba = _tool()
    _write(tmp_path, "BENCH_r06.json", [json.dumps({
        "metric": "merge_ops_per_sec_per_chip", "value": 1,
        "pipeline_serving_ops_per_sec": 2,
        "deli_scribe_e2e_ops_per_sec": 3,
        "fleet_mesh_ops_per_sec": 4,
    })])
    assert cba.check(str(tmp_path)) == 0


def test_metrics_may_span_multiple_record_lines(tmp_path):
    cba = _tool()
    _write(tmp_path, "BENCH_r07.json", [
        "some non-json warning line",
        '{"metric": "pipeline_serving_ops_per_sec", '
        '"pipeline_serving_ops_per_sec": 2}',
        '{"deli_scribe_e2e_ops_per_sec": 3}',
        '{"fleet_mesh_ops_per_sec": 4}',
        '{"tree_moves_device_fraction": 0.97}',
    ])
    assert cba.check(str(tmp_path)) == 0


def test_r7_requires_tree_moves_fraction(tmp_path):
    """An r7+ artifact with the serving trio but no config-3c-moves
    device fraction is incomplete — the device-native move marks number
    must be driver-captured."""
    cba = _tool()
    _write(tmp_path, "BENCH_r07.json", [json.dumps({
        "pipeline_serving_ops_per_sec": 2,
        "deli_scribe_e2e_ops_per_sec": 3,
        "fleet_mesh_ops_per_sec": 4,
    })])
    assert cba.check(str(tmp_path)) == 1


def test_r6_exempt_from_tree_moves_fraction(tmp_path):
    """The r6 artifact predates the metric: the serving trio alone
    passes (per-key since-round gating, not one global baseline)."""
    cba = _tool()
    _write(tmp_path, "BENCH_r06.json", [json.dumps({
        "pipeline_serving_ops_per_sec": 2,
        "deli_scribe_e2e_ops_per_sec": 3,
        "fleet_mesh_ops_per_sec": 4,
    })])
    assert cba.check(str(tmp_path)) == 0


def test_r9_requires_observability_keys(tmp_path):
    """An r9+ artifact must carry the sampled-frame stage decomposition
    AND the per-shard occupancy lanes from the single-readback telemetry
    scrape — the prior headline keys alone are incomplete."""
    cba = _tool()
    prior = {
        "pipeline_serving_ops_per_sec": 2,
        "deli_scribe_e2e_ops_per_sec": 3,
        "fleet_mesh_ops_per_sec": 4,
        "tree_moves_device_fraction": 0.97,
    }
    _write(tmp_path, "BENCH_r09.json", [json.dumps(prior)])
    assert cba.check(str(tmp_path)) == 1
    # One of the pair is not enough.
    _write(tmp_path, "BENCH_r09.json", [json.dumps(dict(
        prior, serving_stage_spans_ms={"deli": 0.2, "total": 4.5},
    ))])
    assert cba.check(str(tmp_path)) == 1
    _write(tmp_path, "BENCH_r09.json", [json.dumps(dict(
        prior,
        serving_stage_spans_ms={"deli": 0.2, "total": 4.5},
        device_shard_occupancy={"128": [5, 5, 5, 5]},
    ))])
    assert cba.check(str(tmp_path)) == 0


def test_r8_exempt_from_observability_keys(tmp_path):
    """Per-key since-round gating: an r8 artifact predates the
    observability pair and passes with the four prior keys."""
    cba = _tool()
    _write(tmp_path, "BENCH_r08.json", [json.dumps({
        "pipeline_serving_ops_per_sec": 2,
        "deli_scribe_e2e_ops_per_sec": 3,
        "fleet_mesh_ops_per_sec": 4,
        "tree_moves_device_fraction": 0.97,
    })])
    assert cba.check(str(tmp_path)) == 0


def test_r10_requires_pump_keys(tmp_path):
    """An r10+ artifact must carry the continuous-pump pair — the
    parity-pinned pump throughput and the measured device idle fraction
    — on top of every earlier gated key."""
    cba = _tool()
    prior = {
        "pipeline_serving_ops_per_sec": 2,
        "deli_scribe_e2e_ops_per_sec": 3,
        "fleet_mesh_ops_per_sec": 4,
        "tree_moves_device_fraction": 0.97,
        "serving_stage_spans_ms": {"deli": 0.2, "total": 4.5},
        "device_shard_occupancy": {"128": [5, 5, 5, 5]},
    }
    _write(tmp_path, "BENCH_r10.json", [json.dumps(prior)])
    assert cba.check(str(tmp_path)) == 1
    # One of the pair is not enough.
    _write(tmp_path, "BENCH_r10.json", [json.dumps(dict(
        prior, serving_pump_ops_per_sec=123456,
    ))])
    assert cba.check(str(tmp_path)) == 1
    _write(tmp_path, "BENCH_r10.json", [json.dumps(dict(
        prior,
        serving_pump_ops_per_sec=123456,
        serving_pump_device_idle_frac=0.12,
    ))])
    assert cba.check(str(tmp_path)) == 0


def test_r9_exempt_from_pump_keys(tmp_path):
    """Per-key since-round gating: an r9 artifact predates the pump pair
    and passes with the six prior keys."""
    cba = _tool()
    _write(tmp_path, "BENCH_r09.json", [json.dumps({
        "pipeline_serving_ops_per_sec": 2,
        "deli_scribe_e2e_ops_per_sec": 3,
        "fleet_mesh_ops_per_sec": 4,
        "tree_moves_device_fraction": 0.97,
        "serving_stage_spans_ms": {"deli": 0.2, "total": 4.5},
        "device_shard_occupancy": {"128": [5, 5, 5, 5]},
    })])
    assert cba.check(str(tmp_path)) == 0


def test_r11_requires_fault_recovery_key(tmp_path):
    """An r11+ artifact must carry the chaos-recovery headline — serving
    throughput under the standard 1% fault mix, parity-asserted."""
    cba = _tool()
    prior = {
        "pipeline_serving_ops_per_sec": 2,
        "deli_scribe_e2e_ops_per_sec": 3,
        "fleet_mesh_ops_per_sec": 4,
        "tree_moves_device_fraction": 0.97,
        "serving_stage_spans_ms": {"deli": 0.2, "total": 4.5},
        "device_shard_occupancy": {"128": [5, 5, 5, 5]},
        "serving_pump_ops_per_sec": 123456,
        "serving_pump_device_idle_frac": 0.12,
    }
    _write(tmp_path, "BENCH_r11.json", [json.dumps(prior)])
    assert cba.check(str(tmp_path)) == 1
    _write(tmp_path, "BENCH_r11.json", [json.dumps(dict(
        prior, fault_recovery_ops_per_sec=54321,
    ))])
    assert cba.check(str(tmp_path)) == 0


def test_r10_exempt_from_fault_recovery_key(tmp_path):
    """Per-key since-round gating: an r10 artifact predates the
    chaos-recovery headline and passes with the eight prior keys."""
    cba = _tool()
    _write(tmp_path, "BENCH_r10.json", [json.dumps({
        "pipeline_serving_ops_per_sec": 2,
        "deli_scribe_e2e_ops_per_sec": 3,
        "fleet_mesh_ops_per_sec": 4,
        "tree_moves_device_fraction": 0.97,
        "serving_stage_spans_ms": {"deli": 0.2, "total": 4.5},
        "device_shard_occupancy": {"128": [5, 5, 5, 5]},
        "serving_pump_ops_per_sec": 123456,
        "serving_pump_device_idle_frac": 0.12,
    })])
    assert cba.check(str(tmp_path)) == 0


def test_r12_requires_frontdoor_keys(tmp_path):
    """An r12+ artifact must carry the continuous-front-door pair — the
    parity-pinned streaming-feed throughput AND the submit→device-commit
    feed latency under continuous feed."""
    cba = _tool()
    prior = {
        "pipeline_serving_ops_per_sec": 2,
        "deli_scribe_e2e_ops_per_sec": 3,
        "fleet_mesh_ops_per_sec": 4,
        "tree_moves_device_fraction": 0.97,
        "serving_stage_spans_ms": {"deli": 0.2, "total": 4.5},
        "device_shard_occupancy": {"128": [5, 5, 5, 5]},
        "serving_pump_ops_per_sec": 123456,
        "serving_pump_device_idle_frac": 0.12,
        "fault_recovery_ops_per_sec": 54321,
    }
    _write(tmp_path, "BENCH_r12.json", [json.dumps(prior)])
    assert cba.check(str(tmp_path)) == 1
    # One of the pair is not enough.
    _write(tmp_path, "BENCH_r12.json", [json.dumps(dict(
        prior, serving_frontdoor_ops_per_sec=222222,
    ))])
    assert cba.check(str(tmp_path)) == 1
    _write(tmp_path, "BENCH_r12.json", [json.dumps(dict(
        prior,
        serving_frontdoor_ops_per_sec=222222,
        serving_feed_latency_ms=1.7,
    ))])
    assert cba.check(str(tmp_path)) == 0


def test_r11_exempt_from_frontdoor_keys(tmp_path):
    """Per-key since-round gating: an r11 artifact predates the
    front-door pair and passes with the nine prior keys."""
    cba = _tool()
    _write(tmp_path, "BENCH_r11.json", [json.dumps({
        "pipeline_serving_ops_per_sec": 2,
        "deli_scribe_e2e_ops_per_sec": 3,
        "fleet_mesh_ops_per_sec": 4,
        "tree_moves_device_fraction": 0.97,
        "serving_stage_spans_ms": {"deli": 0.2, "total": 4.5},
        "device_shard_occupancy": {"128": [5, 5, 5, 5]},
        "serving_pump_ops_per_sec": 123456,
        "serving_pump_device_idle_frac": 0.12,
        "fault_recovery_ops_per_sec": 54321,
    })])
    assert cba.check(str(tmp_path)) == 0


_R12_COMPLETE = {
    "pipeline_serving_ops_per_sec": 2,
    "deli_scribe_e2e_ops_per_sec": 3,
    "fleet_mesh_ops_per_sec": 4,
    "tree_moves_device_fraction": 0.97,
    "serving_stage_spans_ms": {"deli": 0.2, "total": 4.5},
    "device_shard_occupancy": {"128": [5, 5, 5, 5]},
    "serving_pump_ops_per_sec": 123456,
    "serving_pump_device_idle_frac": 0.12,
    "fault_recovery_ops_per_sec": 54321,
    "serving_frontdoor_ops_per_sec": 222222,
    "serving_feed_latency_ms": 1.7,
}


def test_r13_requires_overload_keys(tmp_path):
    """An r13+ artifact must carry the overload-envelope pair — the
    0.5x/1x/2x goodput curve (linear-not-cliff) AND the counted
    load-shedding tier transitions."""
    cba = _tool()
    _write(tmp_path, "BENCH_r13.json", [json.dumps(_R12_COMPLETE)])
    assert cba.check(str(tmp_path)) == 1
    # One of the pair is not enough.
    _write(tmp_path, "BENCH_r13.json", [json.dumps(dict(
        _R12_COMPLETE,
        overload_goodput_curve={"0.5x": 8.0, "1x": 16.0, "2x": 15.5},
    ))])
    assert cba.check(str(tmp_path)) == 1
    _write(tmp_path, "BENCH_r13.json", [json.dumps(dict(
        _R12_COMPLETE,
        overload_goodput_curve={"0.5x": 8.0, "1x": 16.0, "2x": 15.5},
        serving_overload_tier_transitions={"NORMAL->SHED_READS": 1},
    ))])
    assert cba.check(str(tmp_path)) == 0


def test_r12_exempt_from_overload_keys(tmp_path):
    """Per-key since-round gating: an r12 artifact predates the overload
    pair and passes with the eleven prior keys."""
    cba = _tool()
    _write(tmp_path, "BENCH_r12.json", [json.dumps(_R12_COMPLETE)])
    assert cba.check(str(tmp_path)) == 0


_R13_COMPLETE = dict(
    _R12_COMPLETE,
    overload_goodput_curve={"0.5x": 8.0, "1x": 16.0, "2x": 15.5},
    serving_overload_tier_transitions={"NORMAL->SHED_READS": 1},
)


def test_r14_requires_journal_keys(tmp_path):
    """An r14+ artifact must carry the flight-recorder pair — the
    measured journal-on/journal-off serving overhead AND the per-stage
    p99 tail next to the r9 means."""
    cba = _tool()
    _write(tmp_path, "BENCH_r14.json", [json.dumps(_R13_COMPLETE)])
    assert cba.check(str(tmp_path)) == 1
    # One of the pair is not enough.
    _write(tmp_path, "BENCH_r14.json", [json.dumps(dict(
        _R13_COMPLETE, journal_overhead_frac=0.012,
    ))])
    assert cba.check(str(tmp_path)) == 1
    _write(tmp_path, "BENCH_r14.json", [json.dumps(dict(
        _R13_COMPLETE,
        journal_overhead_frac=0.012,
        serving_stage_p99_ms={"deli": 0.4, "total": 9.1},
    ))])
    assert cba.check(str(tmp_path)) == 0


def test_r13_exempt_from_journal_keys(tmp_path):
    """Per-key since-round gating: an r13 artifact predates the
    flight-recorder pair and passes with the thirteen prior keys."""
    cba = _tool()
    _write(tmp_path, "BENCH_r13.json", [json.dumps(_R13_COMPLETE)])
    assert cba.check(str(tmp_path)) == 0


_R14_COMPLETE = dict(
    _R13_COMPLETE,
    journal_overhead_frac=0.012,
    serving_stage_p99_ms={"deli": 0.4, "total": 9.1},
)


def test_r15_requires_read_fanout_keys(tmp_path):
    """An r15+ artifact must carry the read-tier trio — encode-once
    fan-out throughput, the per-subscriber delivery p99, AND the
    batched-gather amortization number."""
    cba = _tool()
    _write(tmp_path, "BENCH_r15.json", [json.dumps(_R14_COMPLETE)])
    assert cba.check(str(tmp_path)) == 1
    # A subset of the trio is not enough.
    _write(tmp_path, "BENCH_r15.json", [json.dumps(dict(
        _R14_COMPLETE, serving_read_fanout_ops_per_sec=123456,
    ))])
    assert cba.check(str(tmp_path)) == 1
    _write(tmp_path, "BENCH_r15.json", [json.dumps(dict(
        _R14_COMPLETE,
        serving_read_fanout_ops_per_sec=123456,
        serving_read_delivery_p99_ms=2.5,
    ))])
    assert cba.check(str(tmp_path)) == 1
    _write(tmp_path, "BENCH_r15.json", [json.dumps(dict(
        _R14_COMPLETE,
        serving_read_fanout_ops_per_sec=123456,
        serving_read_delivery_p99_ms=2.5,
        reads_per_device_dispatch=64.0,
    ))])
    assert cba.check(str(tmp_path)) == 0


def test_r14_exempt_from_read_fanout_keys(tmp_path):
    """Per-key since-round gating: an r14 artifact predates the
    read-tier trio and passes with the fifteen prior keys."""
    cba = _tool()
    _write(tmp_path, "BENCH_r14.json", [json.dumps(_R14_COMPLETE)])
    assert cba.check(str(tmp_path)) == 0


_R15_COMPLETE = dict(
    _R14_COMPLETE,
    serving_read_fanout_ops_per_sec=123456,
    serving_read_delivery_p99_ms=2.5,
    reads_per_device_dispatch=64.0,
)


def test_r16_requires_profiler_keys(tmp_path):
    """An r16+ artifact must carry the timeline-profiler trio — the
    per-boxcar host tax, the per-lane pump decomposition, AND the
    loop-stall watchdog's lag gauge."""
    cba = _tool()
    _write(tmp_path, "BENCH_r16.json", [json.dumps(_R15_COMPLETE)])
    assert cba.check(str(tmp_path)) == 1
    # A subset of the trio is not enough.
    _write(tmp_path, "BENCH_r16.json", [json.dumps(dict(
        _R15_COMPLETE, serving_host_tax_ms={"p50": 0.4, "p99": 1.2},
    ))])
    assert cba.check(str(tmp_path)) == 1
    _write(tmp_path, "BENCH_r16.json", [json.dumps(dict(
        _R15_COMPLETE,
        serving_host_tax_ms={"p50": 0.4, "p99": 1.2},
        pump_lane_profile={"host_stage": 2.5, "loop_other": 0.7},
    ))])
    assert cba.check(str(tmp_path)) == 1
    _write(tmp_path, "BENCH_r16.json", [json.dumps(dict(
        _R15_COMPLETE,
        serving_host_tax_ms={"p50": 0.4, "p99": 1.2},
        pump_lane_profile={"host_stage": 2.5, "loop_other": 0.7},
        event_loop_lag_ms=0.8,
    ))])
    assert cba.check(str(tmp_path)) == 0


def test_r15_exempt_from_profiler_keys(tmp_path):
    """Per-key since-round gating: an r15 artifact predates the
    timeline-profiler trio and passes with the eighteen prior keys."""
    cba = _tool()
    _write(tmp_path, "BENCH_r15.json", [json.dumps(_R15_COMPLETE)])
    assert cba.check(str(tmp_path)) == 0


_R16_COMPLETE = dict(
    _R15_COMPLETE,
    serving_host_tax_ms={"p50": 0.4, "p99": 1.2},
    pump_lane_profile={"host_stage": 2.5, "loop_other": 0.7},
    event_loop_lag_ms=0.8,
)


def test_r19_requires_residency_keys(tmp_path):
    """An r19+ artifact must carry the residency pair — the cold-op wake
    latency p99 AND the fleet-as-cache hit ratio (the fleet-as-cache
    headline numbers must be driver-captured)."""
    cba = _tool()
    _write(tmp_path, "BENCH_r19.json", [json.dumps(_R16_COMPLETE)])
    assert cba.check(str(tmp_path)) == 1
    # One of the pair is not enough.
    _write(tmp_path, "BENCH_r19.json", [json.dumps(dict(
        _R16_COMPLETE, residency_wake_p99_ms=12.5,
    ))])
    assert cba.check(str(tmp_path)) == 1
    _write(tmp_path, "BENCH_r19.json", [json.dumps(dict(
        _R16_COMPLETE,
        residency_wake_p99_ms=12.5,
        residency_hit_ratio=0.92,
    ))])
    assert cba.check(str(tmp_path)) == 0


def test_r18_exempt_from_residency_keys(tmp_path):
    """Per-key since-round gating: an r18 artifact predates the
    residency pair and passes with the twenty-one prior keys."""
    cba = _tool()
    _write(tmp_path, "BENCH_r18.json", [json.dumps(_R16_COMPLETE)])
    assert cba.check(str(tmp_path)) == 0


def test_newest_round_governs(tmp_path):
    cba = _tool()
    _write(tmp_path, "BENCH_r05.json", ['{"metric": "old"}'])
    _write(tmp_path, "BENCH_r06.json", ['{"metric": "new"}'])
    assert cba.check(str(tmp_path)) == 1  # r6 is newest and incomplete


def test_pre_serving_rounds_exempt(tmp_path):
    cba = _tool()
    _write(tmp_path, "BENCH_r05.json", ['{"metric": "old"}'])
    assert cba.check(str(tmp_path)) == 0


def test_repo_root_artifacts_pass():
    """The gate must hold on the repo as committed right now."""
    cba = _tool()
    root = os.path.join(os.path.dirname(__file__), "..")
    assert cba.check(root) == 0
