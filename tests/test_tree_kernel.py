"""Device tree-changeset kernel vs the host mark algebra.

Every law pinned by ``test_tree_marks.py`` re-checks here THROUGH the dense
device kernel (vmapped/jitted), plus direct parity: random host changesets
lowered to the dense IR must produce identical documents through apply/
rebase/invert/compose on both implementations — INCLUDING move-bearing
changesets (r7: mout/min lower into the dense move lanes; the four laws
are re-fuzzed on move-bearing inputs below). On CI this runs on the
virtual CPU backend; the bench artifact runs the same kernels on real TPU.
"""

import numpy as np
import pytest

from fluidframework_tpu.ops import tree_kernel as TK
from fluidframework_tpu.tree import marks as M
from test_tree_marks import (
    random_change,
    random_change_with_moves,
    random_state,
)

LC, PC = 48, 48


def dense(c):
    return TK.from_marks(c, LC, PC)


def run_apply(doc, c):
    ids, L = TK.doc_to_dense(doc, LC)
    dc, _ = dense(c)
    out, out_L = TK.batched_apply(
        ids[None], np.asarray([L], np.int32), tree_map_batch(dc)
    )
    return TK.dense_to_doc(out[0], out_L[0])


def tree_map_batch(dc):
    return TK.DenseChange(*[x[None] for x in dc])


@pytest.mark.parametrize("seed", range(25))
def test_apply_parity(seed):
    rng = np.random.default_rng(seed)
    s = random_state(rng)
    c = random_change(rng, s)
    assert run_apply(s, c) == M.apply(s, c)


@pytest.mark.parametrize("seed", range(25))
def test_invert_roundtrip_on_device(seed):
    rng = np.random.default_rng(seed + 500)
    s = random_state(rng)
    c = random_change(rng, s)
    ids, L = TK.doc_to_dense(s, LC)
    dc, _ = dense(c)
    Lb = np.asarray([L], np.int32)
    out, out_L = TK.batched_apply(ids[None], Lb, tree_map_batch(dc))
    inv = TK.batched_invert(ids[None], Lb, tree_map_batch(dc))
    back, back_L = TK.batched_apply(out, out_L, inv)
    assert TK.dense_to_doc(back[0], back_L[0]) == s


@pytest.mark.parametrize("seed", range(25))
def test_rebase_convergence_on_device(seed):
    """Two-client law through the device kernel: apply(a) + rebase(b, a)
    equals apply(b) + rebase(a, b, mirrored tie)."""
    rng = np.random.default_rng(seed + 3000)
    s = random_state(rng)
    a = random_change(rng, s)
    b = random_change(rng, s)
    ids, L = TK.doc_to_dense(s, LC)
    Lb = np.asarray([L], np.int32)
    da, db = tree_map_batch(dense(a)[0]), tree_map_batch(dense(b)[0])
    sa, La_ = TK.batched_apply(ids[None], Lb, da)
    b_on_a = TK.batched_rebase(db, da, Lb, False)
    via_a, via_a_L = TK.batched_apply(sa, La_, b_on_a)
    sb, Lb_ = TK.batched_apply(ids[None], Lb, db)
    a_on_b = TK.batched_rebase(da, db, Lb, True)
    via_b, via_b_L = TK.batched_apply(sb, Lb_, a_on_b)
    got_a = TK.dense_to_doc(via_a[0], via_a_L[0])
    got_b = TK.dense_to_doc(via_b[0], via_b_L[0])
    assert got_a == got_b
    # And both match the host algebra.
    assert got_a == M.apply(M.apply(s, a), M.rebase(b, a))


@pytest.mark.parametrize("seed", range(25))
def test_compose_parity(seed):
    rng = np.random.default_rng(seed + 1000)
    s = random_state(rng)
    a = random_change(rng, s)
    mid = M.apply(s, a)
    b = random_change(rng, mid)
    ids, L = TK.doc_to_dense(s, LC)
    Lb = np.asarray([L], np.int32)
    da = tree_map_batch(dense(a)[0])
    db = tree_map_batch(dense(b)[0])
    ab, ovf = TK.batched_compose(da, db, Lb)
    assert int(ovf[0]) == 0
    out, out_L = TK.batched_apply(ids[None], Lb, ab)
    assert TK.dense_to_doc(out[0], out_L[0]) == M.apply(s, M.compose(a, b))


@pytest.mark.parametrize("seed", range(15))
def test_compose_associative_on_device(seed):
    rng = np.random.default_rng(seed + 2000)
    s = random_state(rng)
    a = random_change(rng, s)
    s1 = M.apply(s, a)
    b = random_change(rng, s1)
    s2 = M.apply(s1, b)
    c = random_change(rng, s2)
    ids, L = TK.doc_to_dense(s, LC)
    Lb = np.asarray([L], np.int32)
    da, db, dc = (tree_map_batch(dense(x)[0]) for x in (a, b, c))
    ab, _ = TK.batched_compose(da, db, Lb)
    left, _ = TK.batched_compose(ab, dc, Lb)
    La1 = TK.out_len(TK.DenseChange(*[x[0] for x in da]), np.int32(L))
    bc, _ = TK.batched_compose(db, dc, np.asarray([La1], np.int32))
    right, _ = TK.batched_compose(da, bc, Lb)
    o1, l1 = TK.batched_apply(ids[None], Lb, left)
    o2, l2 = TK.batched_apply(ids[None], Lb, right)
    assert TK.dense_to_doc(o1[0], l1[0]) == TK.dense_to_doc(o2[0], l2[0])
    assert TK.dense_to_doc(o1[0], l1[0]) == M.apply(
        s, M.compose(M.compose(a, b), c)
    )


def test_rebase_insert_tie_later_lands_left_on_device():
    s = [1, 2]
    a = [M.skip(1), M.insert([10])]
    b = [M.skip(1), M.insert([20])]
    ids, L = TK.doc_to_dense(s, LC)
    Lb = np.asarray([L], np.int32)
    da, db = tree_map_batch(dense(a)[0]), tree_map_batch(dense(b)[0])
    sa, La_ = TK.batched_apply(ids[None], Lb, da)
    merged, mL = TK.batched_apply(sa, La_, TK.batched_rebase(db, da, Lb, False))
    assert TK.dense_to_doc(merged[0], mL[0]) == [1, 20, 10, 2]


def test_rebase_insert_inside_deleted_range_slides_on_device():
    s = [1, 2, 3, 4]
    o = [M.skip(1), M.delete([2, 3])]
    c = [M.skip(2), M.insert([9])]
    ids, L = TK.doc_to_dense(s, LC)
    Lb = np.asarray([L], np.int32)
    do, dc = tree_map_batch(dense(o)[0]), tree_map_batch(dense(c)[0])
    so, Lo = TK.batched_apply(ids[None], Lb, do)
    out, oL = TK.batched_apply(so, Lo, TK.batched_rebase(dc, do, Lb, False))
    assert TK.dense_to_doc(out[0], oL[0]) == [1, 9, 4]


def test_revive_restores_identical_ids():
    """Revive semantics (reference Revive/ReturnTo marks): del marks carry
    values, so inverting a delete re-inserts the SAME ids at the same
    spots — the detached-content round-trip, through the device kernel."""
    s = [11, 22, 33, 44]
    c = [M.skip(1), M.delete([22, 33])]
    ids, L = TK.doc_to_dense(s, LC)
    Lb = np.asarray([L], np.int32)
    dc = tree_map_batch(dense(c)[0])
    out, out_L = TK.batched_apply(ids[None], Lb, dc)
    assert TK.dense_to_doc(out[0], out_L[0]) == [11, 44]
    inv = TK.batched_invert(ids[None], Lb, dc)
    back, back_L = TK.batched_apply(out, out_L, inv)
    # Identity, not just equal values: the revived cells ARE 22 and 33.
    assert TK.dense_to_doc(back[0], back_L[0]) == [11, 22, 33, 44]


def test_unknown_mark_kind_is_rejected_loudly():
    """Foreign (non-IR) mark kinds must be refused by the dense lowering —
    mout/min are device-native since r7, so only kinds outside the wire
    vocabulary reject, and they reject LOUDLY, never a silent miscompile."""
    with pytest.raises(ValueError, match="outside the sequence-field IR"):
        TK.from_marks([("mvout", [1, 2])], LC, PC)
    # The host algebra rejects them too — never silently insert-coerced,
    # never hung (compose's reader used to spin on zero-length heads).
    with pytest.raises(ValueError, match="outside the sequence-field IR"):
        M.apply([1, 2], [("mvout", [1])])
    with pytest.raises(ValueError, match="outside the sequence-field IR"):
        M.invert([("revive", [1])])
    with pytest.raises(ValueError, match="outside the sequence-field IR"):
        M.compose([M.skip(1)], [("mvout", [9])])
    with pytest.raises(ValueError, match="outside the sequence-field IR"):
        M.rebase([("mvout", [5])], [M.skip(1)])


def test_foreign_mark_kind_falls_back_to_host_path():
    """EditManager's device prefix excludes commits with FOREIGN mark
    kinds (outside the wire IR): they take the host path by contract and
    the fallback is attributed. Move-bearing commits, by contrast, are
    device-eligible since r7 — the has_moves gate is retired."""
    from fluidframework_tpu.tree.edit_manager import Commit, EditManager

    em = EditManager(session=1)
    commits = [
        Commit(session=7, seq=k, ref=k - 1,
               change=[M.insert([(1000 + k, k)])])
        for k in range(1, 6)
    ]
    # A foreign mark kind mid-stream (simulating a future wire form).
    commits[2] = Commit(
        session=7, seq=3, ref=2,
        change=[("mvout", [(1001, 1)])],
    )
    prefix, reason = em._device_prefix_ex(commits)
    assert prefix == 0  # stops before it (2 < DEVICE_MIN_BATCH)
    assert reason == "other_mark"
    # A MOVE commit in the same slot keeps the stream device-eligible:
    # moves ride the EM kernel now.
    commits[2] = Commit(
        session=7, seq=3, ref=2,
        change=M.normalize([
            M.move_out(0, [(1001, 1)]), M.skip(1), M.move_in(0, 1),
        ]),
    )
    assert em._device_prefix(commits) == 5
    commits[2] = Commit(
        session=7, seq=3, ref=2, change=[M.insert([(1003, 3)])]
    )
    assert em._device_prefix(commits) == 5


def test_compose_pool_overflow_flagged():
    """Composing changes whose merged live inserts exceed Pc must raise the
    overflow lane instead of silently truncating (ADVICE r2)."""
    small_pc = 4
    a = [M.insert([21, 22, 23])]
    b = [M.insert([11, 12, 13])]
    da, _ = TK.from_marks(a, LC, small_pc)
    db, _ = TK.from_marks(b, LC, small_pc)
    L = np.asarray([0], np.int32)
    comp, ovf = TK.batched_compose(
        TK.DenseChange(*[np.asarray(x)[None] for x in da]),
        TK.DenseChange(*[np.asarray(x)[None] for x in db]),
        L,
    )
    assert int(ovf[0]) == 1
    # A fitting compose of the same shape stays clean.
    da2, _ = TK.from_marks([M.insert([21, 22])], LC, small_pc)
    db2, _ = TK.from_marks([M.insert([11])], LC, small_pc)
    _, ovf2 = TK.batched_compose(
        TK.DenseChange(*[np.asarray(x)[None] for x in da2]),
        TK.DenseChange(*[np.asarray(x)[None] for x in db2]),
        L,
    )
    assert int(ovf2[0]) == 0


def test_batched_independence():
    """Different changesets in one batch don't interfere (vmap sanity) —
    move-bearing and move-free changesets mixed in one dispatch."""
    rng = np.random.default_rng(42)
    docs, changes = [], []
    for j in range(8):
        s = random_state(rng, 6)
        docs.append(s)
        gen = random_change_with_moves if j % 2 else random_change
        changes.append(gen(rng, s))
    ids = np.stack([TK.doc_to_dense(s, LC)[0] for s in docs])
    Ls = np.asarray([len(s) for s in docs], np.int32)
    dcs = [dense(c)[0] for c in changes]
    batch = TK.DenseChange(
        *[np.stack([np.asarray(getattr(d, f)) for d in dcs])
          for f in TK.DenseChange._fields]
    )
    out, out_L = TK.batched_apply(ids, Ls, batch)
    for i in range(8):
        assert TK.dense_to_doc(out[i], out_L[i]) == M.apply(docs[i], changes[i])


# ---------------------------------------------------------------------------
# Moves through the dense lanes (r7): the four algebra laws re-fuzzed on
# move-bearing inputs — the device mirror of test_tree_marks'
# test_move_laws_fuzz, plus directed capture/splice witnesses.


@pytest.mark.parametrize("seed", range(20))
def test_move_laws_fuzz_on_device(seed):
    """apply / invert-roundtrip / compose-vs-sequential / pairwise rebase
    convergence, all through the dense move lanes."""
    rng = np.random.default_rng(seed + 12000)
    s = random_state(rng)
    a = random_change_with_moves(rng, s)
    ids, L = TK.doc_to_dense(s, LC)
    Lb = np.asarray([L], np.int32)
    da = tree_map_batch(dense(a)[0])
    out, out_L = TK.batched_apply(ids[None], Lb, da)
    want = M.apply(s, a)
    assert TK.dense_to_doc(out[0], out_L[0]) == want
    # invert round trip (the return move)
    inv = TK.batched_invert(ids[None], Lb, da)
    back, back_L = TK.batched_apply(out, out_L, inv)
    assert TK.dense_to_doc(back[0], back_L[0]) == s
    # compose == sequential apply
    b = random_change_with_moves(rng, want)
    db = tree_map_batch(dense(b)[0])
    ab, ovf = TK.batched_compose(da, db, Lb)
    assert int(ovf[0]) == 0
    o2, l2 = TK.batched_apply(ids[None], Lb, ab)
    assert TK.dense_to_doc(o2[0], l2[0]) == M.apply(want, b)
    # pairwise rebase convergence + host parity
    b2 = random_change_with_moves(rng, s)
    db2 = tree_map_batch(dense(b2)[0])
    b_on_a = TK.batched_rebase(db2, da, Lb, False)
    via_a, via_a_L = TK.batched_apply(out, out_L, b_on_a)
    sb, Lb_ = TK.batched_apply(ids[None], Lb, db2)
    a_on_b = TK.batched_rebase(da, db2, Lb, True)
    via_b, via_b_L = TK.batched_apply(sb, Lb_, a_on_b)
    got_a = TK.dense_to_doc(via_a[0], via_a_L[0])
    assert got_a == TK.dense_to_doc(via_b[0], via_b_L[0])
    assert got_a == M.apply(M.apply(s, a), M.rebase(b2, a))


@pytest.mark.parametrize("seed", range(10))
def test_compose_associative_with_moves_on_device(seed):
    rng = np.random.default_rng(seed + 50000)
    s = random_state(rng)
    a = random_change_with_moves(rng, s)
    s1 = M.apply(s, a)
    b = random_change_with_moves(rng, s1)
    s2 = M.apply(s1, b)
    c = random_change_with_moves(rng, s2)
    ids, L = TK.doc_to_dense(s, LC)
    Lb = np.asarray([L], np.int32)
    da, db, dc = (tree_map_batch(dense(x)[0]) for x in (a, b, c))
    ab, _ = TK.batched_compose(da, db, Lb)
    left, _ = TK.batched_compose(ab, dc, Lb)
    La1 = TK.out_len(TK.DenseChange(*[x[0] for x in da]), np.int32(L))
    bc, _ = TK.batched_compose(db, dc, np.asarray([La1], np.int32))
    right, _ = TK.batched_compose(da, bc, Lb)
    o1, l1 = TK.batched_apply(ids[None], Lb, left)
    o2, l2 = TK.batched_apply(ids[None], Lb, right)
    want = M.apply(s, M.compose(M.compose(a, b), c))
    assert TK.dense_to_doc(o1[0], l1[0]) == want
    assert TK.dense_to_doc(o2[0], l2[0]) == want


def test_rebase_marks_follow_moved_content_on_device():
    """c deletes content that over moved: the delete follows the content
    to its destination (moveEffectTable capture, phase 1 of the kernel)."""
    s = [1, 2, 3, 4, 5]
    over = [M.skip(1), M.move_out(0, [2, 3]), M.skip(2), M.move_in(0, 2)]
    c = [M.skip(1), M.delete([2, 3])]
    ids, L = TK.doc_to_dense(s, LC)
    Lb = np.asarray([L], np.int32)
    do, dc = tree_map_batch(dense(over)[0]), tree_map_batch(dense(c)[0])
    so, Lo = TK.batched_apply(ids[None], Lb, do)
    out, oL = TK.batched_apply(so, Lo, TK.batched_rebase(dc, do, Lb, False))
    assert TK.dense_to_doc(out[0], oL[0]) == [1, 4, 5]


def test_rebase_both_move_later_wins_on_device():
    """Both sides move the same unit: the later-sequenced move wins in
    either application order (the c_after both-move cancellation)."""
    s = [1, 2, 3]
    a = [M.move_in(0, 1), M.skip(2), M.move_out(0, [3])]  # 3 to front
    b = [M.skip(2), M.move_out(0, [3]), M.move_in(0, 1)]  # 3 stays-ish
    ids, L = TK.doc_to_dense(s, LC)
    Lb = np.asarray([L], np.int32)
    da, db = tree_map_batch(dense(a)[0]), tree_map_batch(dense(b)[0])
    sa, La_ = TK.batched_apply(ids[None], Lb, da)
    via_a, vaL = TK.batched_apply(
        sa, La_, TK.batched_rebase(db, da, Lb, False)
    )
    sb, Lb_ = TK.batched_apply(ids[None], Lb, db)
    via_b, vbL = TK.batched_apply(
        sb, Lb_, TK.batched_rebase(da, db, Lb, True)
    )
    got = TK.dense_to_doc(via_a[0], vaL[0])
    assert got == TK.dense_to_doc(via_b[0], vbL[0])
    assert got == M.apply(M.apply(s, a), M.rebase(b, a))


def test_attach_stays_at_source_when_region_moves_on_device():
    """An insert positioned inside a region that over moved anchors at
    the source boundary (attaches do not follow moves — the splice's
    boundary map, not the capture table)."""
    s = [1, 2, 3, 4]
    over = [M.skip(1), M.move_out(0, [2, 3]), M.skip(1), M.move_in(0, 2)]
    c = [M.skip(2), M.insert([9])]  # between 2 and 3
    ids, L = TK.doc_to_dense(s, LC)
    Lb = np.asarray([L], np.int32)
    do, dc = tree_map_batch(dense(over)[0]), tree_map_batch(dense(c)[0])
    so, Lo = TK.batched_apply(ids[None], Lb, do)
    out, oL = TK.batched_apply(so, Lo, TK.batched_rebase(dc, do, Lb, False))
    assert TK.dense_to_doc(out[0], oL[0]) == [1, 9, 4, 2, 3]


def test_move_invert_is_return_move_with_same_ids():
    """Inverting a move re-attaches the SAME ids at the source — the
    dense mirror of the host's return-move inversion."""
    s = [11, 22, 33, 44, 55]
    c = [M.skip(1), M.move_out(0, [22, 33]), M.skip(2), M.move_in(0, 2)]
    ids, L = TK.doc_to_dense(s, LC)
    Lb = np.asarray([L], np.int32)
    dc = tree_map_batch(dense(c)[0])
    out, out_L = TK.batched_apply(ids[None], Lb, dc)
    assert TK.dense_to_doc(out[0], out_L[0]) == [11, 44, 55, 22, 33]
    inv = TK.batched_invert(ids[None], Lb, dc)
    back, back_L = TK.batched_apply(out, out_L, inv)
    assert TK.dense_to_doc(back[0], back_L[0]) == [11, 22, 33, 44, 55]
