"""End-to-end: clients ⇄ in-proc ordering service ⇄ DDS channels.

The layer-4 test of SURVEY.md §4: real runtime objects (ContainerRuntime +
SharedString/SharedMap channels) against the in-process LocalFluidService,
including randomized interleaving of flush/delivery (the farm pattern) and
nack behavior.
"""

import numpy as np
import pytest

from fluidframework_tpu.models.shared_map import SharedMap
from fluidframework_tpu.models.shared_string import SharedString
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.local_server import LocalFluidService

ALPHABET = "abcdefghijklmnopqrstuvwxyz"


def make_clients(service, doc_id, n, channel_factory):
    return [
        ContainerRuntime(service, doc_id, channels=(channel_factory(),))
        for _ in range(n)
    ]


def drain_all(runtimes):
    for rt in runtimes:
        rt.flush()
    busy = True
    while busy:
        busy = any(rt.process_incoming() for rt in runtimes)


def test_two_client_string_convergence():
    svc = LocalFluidService()
    a, b = make_clients(svc, "doc", 2, lambda: SharedString("text"))
    sa = a.get_channel("text")
    sb = b.get_channel("text")

    sa.insert_text(0, "hello")
    a.flush()
    drain_all([a, b])
    assert sb.get_text() == "hello"

    # Concurrent edits at the same position.
    sa.insert_text(5, "!")
    sb.insert_text(0, ">> ")
    drain_all([a, b])
    assert sa.get_text() == sb.get_text() == ">> hello!"


def test_remove_and_annotate_convergence():
    svc = LocalFluidService()
    a, b = make_clients(svc, "doc", 2, lambda: SharedString("text"))
    sa, sb = a.get_channel("text"), b.get_channel("text")
    sa.insert_text(0, "abcdef")
    drain_all([a, b])

    sa.remove_range(1, 3)
    sb.annotate(2, 5, 7)
    drain_all([a, b])
    assert sa.get_text() == sb.get_text() == "adef"
    assert sa.annotations() == sb.annotations()


def test_map_lww_and_pending_wins():
    svc = LocalFluidService()
    a, b = make_clients(svc, "doc", 2, lambda: SharedMap("map"))
    ma, mb = a.get_channel("map"), b.get_channel("map")

    ma.set("x", 1)
    mb.set("x", 2)
    # Before delivery each sees its own value.
    assert ma.get("x") == 1 and mb.get("x") == 2
    a.flush()
    b.flush()
    drain_all([a, b])
    # b's set sequenced after a's -> LWW winner is 2, on both.
    assert ma.get("x") == mb.get("x") == 2

    ma.delete("x")
    drain_all([a, b])
    assert not ma.has("x") and not mb.has("x")


def test_late_joiner_catches_up():
    svc = LocalFluidService()
    (a,) = make_clients(svc, "doc", 1, lambda: SharedString("text"))
    sa = a.get_channel("text")
    sa.insert_text(0, "state")
    a.flush()
    a.process_incoming()

    b = ContainerRuntime(svc, "doc", channels=(SharedString("text"),))
    assert b.get_channel("text").get_text() == "state"


def test_nack_on_gap_surfaces():
    svc = LocalFluidService()
    (a,) = make_clients(svc, "doc", 1, lambda: SharedString("text"))
    # Forge a gap by bumping client_seq manually.
    a.get_channel("text").insert_text(0, "x")
    a.client_seq += 5
    a.flush()
    assert a.connection.nacks and a.connection.nacks[0].content_code == 400


def test_signals_fan_out():
    svc = LocalFluidService()
    a, b = make_clients(svc, "doc", 2, lambda: SharedMap("map"))
    a.connection.submit_signal({"presence": "here"})
    assert b.connection.signals[-1].content == {"presence": "here"}
    assert a.connection.signals[-1].content == {"presence": "here"}


@pytest.mark.parametrize("seed", range(4))
def test_runtime_farm(seed):
    """Randomized interleaving over the real service + runtime stack."""
    rng = np.random.default_rng(seed + 100)
    svc = LocalFluidService()
    n = 3
    rts = make_clients(svc, "doc", n, lambda: SharedString("text"))
    strings = [rt.get_channel("text") for rt in rts]

    for _ in range(120):
        act = rng.integers(0, 4)
        i = int(rng.integers(0, n))
        rt, s = rts[i], strings[i]
        length = len(s)
        if act == 0:
            k = int(rng.integers(1, 4))
            s.insert_text(
                int(rng.integers(0, length + 1)),
                "".join(rng.choice(list(ALPHABET), k)),
            )
        elif act == 1 and length > 0:
            x = int(rng.integers(0, length))
            y = int(rng.integers(x + 1, min(length, x + 6) + 1))
            s.remove_range(x, y)
        elif act == 2:
            rt.flush()
        else:
            rt.process_incoming(int(rng.integers(1, 6)))

    drain_all(rts)
    texts = [s.get_text() for s in strings]
    assert all(t == texts[0] for t in texts), f"diverged: {texts}"
    assert all(s.err_flags == 0 for s in strings)


def test_summary_roundtrip_string():
    svc = LocalFluidService()
    a, b = make_clients(svc, "doc", 2, lambda: SharedString("text"))
    sa = a.get_channel("text")
    sa.insert_text(0, "hello world")
    sa.annotate(0, 5, 3)
    sa.remove_range(5, 6)
    drain_all([a, b])

    summary = a.summarize()
    c = ContainerRuntime(svc, "doc2", channels=(SharedString("text"),))
    sc = c.get_channel("text")
    sc.load_core(summary["channels"]["text"])
    assert sc.get_text() == sa.get_text() == "helloworld"
    assert sc.annotations() == sa.annotations()
