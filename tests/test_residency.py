"""Fleet-as-cache residency (r19): the shared heat signal, hibernation,
and bounded-latency wake.

Pinned here:

- ``HeatTracker``'s window-normalized rate — the cold-start-bias fix,
  regression-tested for BOTH consumers (the multi-node rebalancer's doc
  selection and the residency manager's hibernation ordering).
- Hibernate→wake bit parity against a never-evicted run on the dense
  fleet, the 8-device mesh, and the multi-pool (promotion/demotion)
  layout — plus the tier-demotion walk riding the existing scan.
- A move-bearing SharedTree document surviving the hibernate→wake cycle
  through the full pipeline (tree truth rides the durable log; the
  doc's device channels evict and restore bit-identically).
- Wake under concurrent submit over a REAL websocket: a faulted wake
  parks the burst in the bounded pending queue and the retry admits it
  gapless and in order — never dropped, never reordered.
"""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from fluidframework_tpu.parallel.mesh import make_mesh
from fluidframework_tpu.protocol.constants import (
    F_ARG,
    F_LEN,
    F_MSN,
    F_POS1,
    F_POS2,
    F_REF,
    F_SEQ,
    F_TYPE,
    OP_INSERT,
    OP_REMOVE,
    OP_WIDTH,
)
from fluidframework_tpu.protocol.opframe import SeqFrame
from fluidframework_tpu.service import residency
from fluidframework_tpu.service.device_backend import DeviceFleetBackend
from fluidframework_tpu.service.residency import HeatTracker, ResidencyManager
from fluidframework_tpu.testing import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# HeatTracker: the shared decayed-rate signal and the cold-start-bias fix


class TestHeatTracker:
    def test_steady_rate_is_age_invariant(self):
        """A document producing r ops per window scores rate == r at ANY
        age — the property raw accumulators lack (they sweep from r up
        to r/(1-decay) as the doc ages)."""
        h = HeatTracker(decay=0.5)
        for _ in range(12):
            h.touch("d", 3.0)
            assert abs(h.rate("d") - 3.0) < 1e-9
            h.observe_window()

    def test_cold_start_bias_raw_misranks_rate_fixes(self):
        """The regression the extraction fixes: an aged doc at a steady
        3 ops/window accumulates ~6 raw, out-ranking a brand-new doc
        doing 5 ops/window at raw 5 — the normalized rate ranks them
        correctly."""
        h = HeatTracker(decay=0.5)
        for _ in range(10):
            h.touch("aged", 3.0)
            h.observe_window()
        h.touch("aged", 3.0)
        h.touch("young", 5.0)
        assert h.raw("aged") > h.raw("young"), "the bias this test pins"
        assert h.rate("young") > h.rate("aged"), "rate() must fix it"
        assert abs(h.rate("aged") - 3.0) < 1e-6
        assert abs(h.rate("young") - 5.0) < 1e-6

    def test_prune_bounds_the_tracker(self):
        """At a million-document corpus the tracker must not retain
        every id ever touched: entries decay out below the prune floor,
        and a pruned doc that returns is simply new."""
        h = HeatTracker(decay=0.5)
        for i in range(1000):
            h.touch(f"d{i}")
        assert len(h) == 1000
        for _ in range(20):
            h.observe_window()
        assert len(h) == 0
        h.touch("d0")
        assert h.export("d0") == (1.0, 0)  # windows restarted

    def test_export_adopt_roundtrip_preserves_rate(self):
        a = HeatTracker(decay=0.5)
        for _ in range(6):
            a.touch("d", 4.0)
            a.observe_window()
        a.touch("d", 4.0)
        b = HeatTracker(decay=0.5)
        b.adopt("d", *a.export("d"))
        assert b.rate("d") == a.rate("d")
        a.forget("d")
        assert a.rate("d") == 0.0


# ---------------------------------------------------------------------------
# Consumer 1: the multi-node rebalancer — normalized doc selection and
# the migration heat hand-off


class TestRebalanceHeat:
    def _cluster(self, n=2):
        from fluidframework_tpu.service.multinode import NodeCluster

        t = [0.0]
        return NodeCluster(n_nodes=n, clock=lambda: t[0])

    def test_rebalance_migrates_young_hot_doc_not_aged_lukewarm(self):
        """The cold-start-bias regression at the rebalancer: node 0 owns
        an aged 3-ops/window doc (raw ~6) and a brand-new 5-ops/window
        doc (raw 5). The pre-r19 raw key would migrate the AGED doc; the
        normalized rate migrates the genuinely hotter young one."""
        c = self._cluster()
        n0 = c.nodes[0]
        assert n0.try_own("aged") and n0.try_own("young")
        for _ in range(10):
            n0.heat.touch("aged", 3.0)
            n0.heat.observe_window()
        n0.heat.touch("aged", 3.0)
        n0.heat.touch("young", 5.0)
        # The compatibility view still shows the raw accumulators —
        # and the bias the raw key suffered:
        assert n0.op_rate["aged"] > n0.op_rate["young"]
        moves = c.rebalance()
        assert [m[0] for m in moves] == [("young")], (
            "rebalance must select by normalized rate, not raw decay mass"
        )
        assert moves[0][1:] == ("node-0", "node-1")

    def test_migration_hands_heat_to_new_owner(self):
        """A migrated doc must not restart cold-start normalization on
        the destination: its (raw, windows) ride the move, then age with
        the pass's decay like everything else."""
        c = self._cluster()
        n0, n1 = c.nodes
        assert n0.try_own("aged") and n0.try_own("young")
        for _ in range(10):
            n0.heat.touch("aged", 3.0)
            n0.heat.observe_window()
        n0.heat.touch("aged", 3.0)
        n0.heat.touch("young", 5.0)
        c.rebalance()
        # Exported at (5.0, windows=0), adopted, then one aging window:
        assert n1.heat.export("young") == (2.5, 1)
        assert n0.heat.raw("young") == 0.0, "old owner forgot the doc"
        assert "young" not in n0.op_rate

    def test_op_rate_view_and_lifecycle_compat(self):
        """The pre-r19 ``op_rate`` dict shape survives as a read-only
        view: ``.get`` on unknown docs, emptied by kill()."""
        c = self._cluster()
        n0 = c.nodes[0]
        assert n0.try_own("d")
        n0.heat.touch("d", 2.0)
        assert n0.op_rate.get("d") == 2.0
        assert n0.op_rate.get("nope") is None
        n0.kill()
        assert n0.op_rate == {}


# ---------------------------------------------------------------------------
# Consumer 2: the residency manager — same signal, same normalization


class TestResidencySharedSignal:
    def test_hibernation_candidates_order_by_normalized_rate(self):
        """Candidates come back coldest-first by the SAME rate() both
        consumers share — an aged lukewarm doc hibernates before a
        young hot one even though its raw accumulator is larger."""
        rm = ResidencyManager(heat=HeatTracker(decay=0.5), heat_floor=10.0)
        rm.note_admit("aged")
        rm.note_admit("young")
        for _ in range(10):
            rm.heat.touch("aged", 3.0)
            rm.heat.observe_window()
        rm.heat.touch("aged", 3.0)
        rm.heat.touch("young", 5.0)
        assert rm.heat.raw("aged") > rm.heat.raw("young")
        rm.mark_idle("aged")
        rm.mark_idle("young")
        assert rm.hibernation_candidates(want=2) == ["aged", "young"]

    def test_heat_floor_guards_hot_docs(self):
        """Without capacity pressure, a doc above the heat floor never
        hibernates no matter how long it sits clientless."""
        rm = ResidencyManager(heat=HeatTracker(), heat_floor=0.5)
        rm.note_admit("hot")
        rm.heat.touch("hot", 50.0)
        rm.mark_idle("hot")
        assert rm.hibernation_candidates(want=8) == []

    def test_hit_ratio_accounting(self):
        rm = ResidencyManager(heat=HeatTracker())
        rm.note_admit("d")
        for _ in range(3):
            assert rm.note_op("d")
        rm.begin_hibernate("d")
        rm.finish_hibernate("d", ok=True)
        assert rm.note_op("d") is False  # the miss that triggers a wake
        assert rm.hit_ratio() == 0.75


# ---------------------------------------------------------------------------
# Hibernate -> wake bit parity against a never-evicted run


def _feed(be, n_ch, k, r):
    ar = np.arange(k, dtype=np.int32)
    for i in range(n_ch):
        rows = np.zeros((k, OP_WIDTH), np.int32)
        rows[:, F_TYPE] = OP_INSERT
        rows[:, F_LEN] = 1
        rows[:, F_SEQ] = r * k + 1 + ar
        rows[:, F_REF] = r * k
        rows[:, F_ARG] = r * k + 1 + ar
        texts = tuple(chr(97 + (r * k + j) % 26) for j in range(k))
        be.enqueue_frame(f"d{i}", SeqFrame("s", 0, 1, rows, texts, 0.0))


def _assert_state_parity(a: DeviceFleetBackend, b: DeviceFleetBackend):
    assert sorted(a.fleet.pools) == sorted(b.fleet.pools)
    for cap, pool_a in a.fleet.pools.items():
        pool_b = b.fleet.pools[cap]
        for name, x, y in zip(
            pool_a.state._fields, pool_a.state, pool_b.state
        ):
            assert bool(jnp.array_equal(x, y)), (cap, name)


def _run(be, n_ch, k, rounds, hibernate_at=None, doc="d0"):
    """Feed ``rounds`` rounds; after round ``hibernate_at`` evict ``doc``
    (the next round's first op wakes it)."""
    woke = False
    for r in range(rounds):
        _feed(be, n_ch, k, r)
        be.flush()
        if hibernate_at is not None and r == hibernate_at:
            assert be.hibernate_doc(doc) is True
            assert be.residency.state(doc) == residency.COLD
            assert be.fleet.placement[be._index[(doc, "s")]] is None
            woke = True
    be.collect_now()
    if woke:
        assert be.residency.stats()["wakes"].get("ok", 0) >= 1


class TestWakeParity:
    def test_dense(self):
        """Hibernate d0 mid-stream, wake it on the next round's first op:
        pool states, served text, and totals are bit-identical to the
        run that never evicted."""
        n_ch, k, rounds = 6, 4, 5
        hib = DeviceFleetBackend(capacity=64, pump_mode=True)
        ref = DeviceFleetBackend(capacity=64, pump_mode=True)
        _run(hib, n_ch, k, rounds, hibernate_at=2)
        _run(ref, n_ch, k, rounds)
        assert hib.ops_applied == ref.ops_applied == n_ch * k * rounds
        _assert_state_parity(hib, ref)
        assert hib.text("d0", "s") == ref.text("d0", "s")
        assert hib.stats()["docs_with_errors"] == 0
        assert hib.stats()["hibernations"] == 1

    def test_mesh(self):
        """Same pin on the 8-device virtual mesh: eviction and restore
        round-trip the sharded pool layout bit-identically."""
        mesh = make_mesh()
        n_ch, k, rounds = 16, 4, 4
        hib = DeviceFleetBackend(capacity=64, mesh=mesh, pump_mode=True)
        ref = DeviceFleetBackend(capacity=64, mesh=mesh, pump_mode=True)
        _run(hib, n_ch, k, rounds, hibernate_at=1, doc="d3")
        _run(ref, n_ch, k, rounds)
        assert hib.ops_applied == ref.ops_applied == n_ch * k * rounds
        _assert_state_parity(hib, ref)
        assert hib.text("d3", "s") == ref.text("d3", "s")

    def test_multi_pool_promoted_doc(self):
        """A doc promoted past its base tier hibernates out of the BIG
        pool and wakes back into it — the cold record carries the
        promoted-capacity state, and parity holds lane for lane."""
        n_ch, k, rounds = 2, 8, 8
        hib = DeviceFleetBackend(
            capacity=16, max_capacity=256, pump_mode=True
        )
        ref = DeviceFleetBackend(
            capacity=16, max_capacity=256, pump_mode=True
        )
        _run(hib, n_ch, k, rounds, hibernate_at=5)
        _run(ref, n_ch, k, rounds)
        assert hib.fleet.migrations > 0, "the stream must really promote"
        cap, _slot = hib.fleet.placement[hib._index[("d0", "s")]]
        assert cap > 16, "d0 must wake back into the promoted tier"
        assert hib.ops_applied == ref.ops_applied == n_ch * k * rounds
        _assert_state_parity(hib, ref)
        assert hib.text("d0", "s") == ref.text("d0", "s")

    def test_demotion_rides_the_scan_then_wake_parity(self):
        """The capacity-tier demotion walk (the inverse of promotion,
        riding the SAME one-boxcar-stale scan): a promoted doc whose
        live rows fall below the low-water mark after the collab window
        passes its removes steps back down a tier — and a hibernate→wake
        cycle after the demotion still restores bit-identical state."""

        def build():
            be = DeviceFleetBackend(
                capacity=16, max_capacity=256, pump_mode=True,
                compact_every=2,
            )
            k = 8
            for r in range(3):  # promote d0 past the base tier
                _feed(be, 1, k, r)
                be.flush()
            rm = np.zeros((1, OP_WIDTH), np.int32)
            rm[0, F_TYPE] = OP_REMOVE
            rm[0, F_POS1], rm[0, F_POS2] = 0, 22
            rm[0, F_SEQ], rm[0, F_REF], rm[0, F_MSN] = 25, 24, 25
            be.enqueue_frame("d0", SeqFrame("s", 0, 1, rm, (), 0.0))
            be.flush()
            for j in range(6):  # window past the remove; keep scans coming
                one = np.zeros((1, OP_WIDTH), np.int32)
                one[0, F_TYPE] = OP_INSERT
                one[0, F_LEN] = 1
                one[0, F_SEQ] = 26 + j
                one[0, F_REF] = 25 + j
                one[0, F_ARG] = 26 + j
                one[0, F_MSN] = 26 + j
                be.enqueue_frame(
                    "d0", SeqFrame("s", 0, 1, one, ("z",), 0.0)
                )
                be.flush()
            be.collect_now()
            return be

        hib = build()
        ref = build()
        assert hib.fleet.stats()["demotions"] > 0
        idx = hib._index[("d0", "s")]
        cap, _slot = hib.fleet.placement[idx]
        assert cap == 16, "d0 must have stepped back down to the base tier"
        # Now the hibernate→wake cycle on the demoted doc:
        assert hib.hibernate_doc("d0") is True
        one = np.zeros((1, OP_WIDTH), np.int32)
        one[0, F_TYPE] = OP_INSERT
        one[0, F_LEN] = 1
        one[0, F_SEQ], one[0, F_REF], one[0, F_ARG] = 32, 31, 32
        one[0, F_MSN] = 32
        for be in (hib, ref):
            be.enqueue_frame("d0", SeqFrame("s", 0, 1, one, ("!",), 0.0))
            be.flush()
            be.collect_now()
        assert hib.residency.state("d0") == residency.RESIDENT
        _assert_state_parity(hib, ref)
        assert hib.text("d0", "s") == ref.text("d0", "s")
        assert hib.stats()["docs_with_errors"] == 0


# ---------------------------------------------------------------------------
# The full pipeline: a move-bearing SharedTree doc through hibernate->wake


def _drain(rts):
    for rt in rts:
        rt.flush()
    while any(rt.process_incoming() for rt in rts):
        pass


def _force_hibernate(svc, doc_id, sweeps=12):
    """Run sweeps until the doc's heat decays under the floor and the
    sweep takes it (each sweep closes one decay window)."""
    for _ in range(sweeps):
        if doc_id in svc.hibernate_sweep():
            return True
    return False


class TestPipelineHibernation:
    def test_move_bearing_shared_tree_doc_survives_hibernate_wake(self):
        """A doc carrying a SharedTree (with first-class moves) AND a
        device-backed string channel hibernates once idle and wakes on
        the next op. Parity against a never-hibernated service run of
        the identical edit script: same tree view (moves included), same
        device channel state, and a fresh catch-up client converges."""
        from fluidframework_tpu.models.shared_string import SharedString
        from fluidframework_tpu.runtime.container import ContainerRuntime
        from fluidframework_tpu.service.pipeline import PipelineFluidService
        from fluidframework_tpu.tree.shared_tree import SharedTree

        def script(svc, hibernate):
            a = ContainerRuntime(
                svc, "doc",
                channels=(SharedTree("t"), SharedString("s")),
            )
            ta, sa = a.get_channel("t"), a.get_channel("s")
            sa.insert_text(0, "tree doc")
            for i in range(6):
                ta.insert_nodes(len(ta.get()), [f"n{i}"])
                _drain([a])
            ta.move_nodes(0, 2, 4)  # the first-class move
            _drain([a])
            stash = a.get_pending_local_state()
            a.disconnect()
            svc.pump()
            if hibernate:
                assert svc.doc_is_idle("doc")
                assert _force_hibernate(svc, "doc"), "sweep must take it"
                assert svc.device.residency.state("doc") == residency.COLD
                # A durable pointer landed for the wake-independent path:
                assert svc.read_tier.latest.latest_handle("doc") is not None
            # The user reopens the stashed session: their first edit is
            # the first op the doc has seen — on the hibernated service
            # it wakes the doc through the pending queue.
            b = ContainerRuntime.rehydrate(
                svc, "doc", stash,
                channels=(SharedTree("t"), SharedString("s")),
            )
            b.process_incoming()
            tb, sb = b.get_channel("t"), b.get_channel("s")
            tb.insert_nodes(0, ["woke"])
            sb.insert_text(0, "! ")
            _drain([b])
            return b, tb.get(), svc.device_text("doc", "s")

        svc_h = PipelineFluidService(n_partitions=2)
        svc_r = PipelineFluidService(n_partitions=2)
        _b_h, tree_h, text_h = script(svc_h, hibernate=True)
        _b_r, tree_r, text_r = script(svc_r, hibernate=False)
        assert tree_h == tree_r
        assert tree_h == ["woke", "n2", "n3", "n4", "n5", "n0", "n1"], (
            "the pre-hibernation moves must survive the wake"
        )
        assert text_h == text_r == "! tree doc"
        assert svc_h.device.residency.state("doc") == residency.RESIDENT
        assert svc_h.device.residency.stats()["wakes"].get("ok", 0) >= 1
        # Device channel state parity, key for key (slot layout may
        # differ between independent services; the doc state may not):
        keys = [k for k in svc_h.device.channels() if k[0] == "doc"]
        st_h = svc_h.device.doc_states(keys)
        st_r = svc_r.device.doc_states(keys)
        for key in keys:
            for name, x, y in zip(
                st_h[key]._fields, st_h[key], st_r[key]
            ):
                assert bool(jnp.array_equal(x, y)), (key, name)

    def test_cold_doc_serves_reads_without_waking(self):
        """Snapshot reads of a COLD doc serve from the cold record —
        the read tier never burns a fleet slot on a doc nobody is
        writing to."""
        from fluidframework_tpu.models.shared_string import SharedString
        from fluidframework_tpu.runtime.container import ContainerRuntime
        from fluidframework_tpu.service.pipeline import PipelineFluidService

        svc = PipelineFluidService(n_partitions=2)
        a = ContainerRuntime(svc, "doc", channels=(SharedString("s"),))
        a.get_channel("s").insert_text(0, "cold read")
        _drain([a])
        a.disconnect()
        svc.pump()
        assert _force_hibernate(svc, "doc")
        assert svc.device_text("doc", "s") == "cold read"
        assert svc.device.residency.state("doc") == residency.COLD, (
            "a read must not wake the doc"
        )


# ---------------------------------------------------------------------------
# Wake under concurrent submit over a REAL websocket: the bounded pending
# queue admits the burst gapless and in order


class TestWakeOverWebsocket:
    def test_wake_under_concurrent_submit_pins_pending_order(self):
        from fluidframework_tpu.drivers.network_driver import (
            NetworkFluidService,
        )
        from fluidframework_tpu.models.shared_string import SharedString
        from fluidframework_tpu.protocol.types import MessageType
        from fluidframework_tpu.runtime.container import ContainerRuntime
        from fluidframework_tpu.service.network_server import (
            FluidNetworkServer,
        )
        from fluidframework_tpu.service.pipeline import PipelineFluidService

        svc = PipelineFluidService(n_partitions=2)
        srv = FluidNetworkServer(service=svc, residency_sweep_s=0.01)
        srv.start()
        try:
            def drain_net(rts, timeout=10.0):
                for rt in rts:
                    rt.flush()
                deadline = time.monotonic() + timeout
                quiet = 0
                while time.monotonic() < deadline and quiet < 3:
                    if any(rt.process_incoming() for rt in rts):
                        quiet = 0
                    else:
                        quiet += 1
                        time.sleep(0.02)

            # Seed the doc, then go idle: the server's ticker sweep
            # hibernates it off the serving loop.
            net_a = NetworkFluidService("127.0.0.1", srv.port)
            a = ContainerRuntime(
                net_a, "wakedoc", channels=(SharedString("s"),)
            )
            a.get_channel("s").insert_text(0, "hello ")
            drain_net([a])
            a.disconnect()
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                if svc.device.residency.state("wakedoc") == residency.COLD:
                    break
                time.sleep(0.02)
            assert svc.device.residency.state("wakedoc") == residency.COLD
            assert srv.residency_sweeps > 0

            # A bystander doc with a LIVE client — it must keep serving
            # while the wake is in flight (and never hibernate).
            net_c = NetworkFluidService("127.0.0.1", srv.port)
            c = ContainerRuntime(
                net_c, "busydoc", channels=(SharedString("s"),)
            )
            c.get_channel("s").insert_text(0, "busy")
            drain_net([c])

            # Fault the FIRST wake attempt: the burst's head op parks,
            # the following ops park behind it in arrival order, and the
            # retry (the next park / the quiescence flush) admits the
            # whole queue as a normal gapless boxcar.
            faults.arm("doc.wake", faults.FailN(1))
            net_b = NetworkFluidService("127.0.0.1", srv.port)
            b = ContainerRuntime(
                net_b, "wakedoc", channels=(SharedString("s"),)
            )
            b.process_incoming()
            sb = b.get_channel("s")
            for i in range(4):  # the concurrent-submit burst
                sb.insert_text(len(sb.get_text()), f"w{i}")
                b.flush()
            c.get_channel("s").insert_text(4, "!")  # concurrent traffic
            drain_net([b, c])
            faults.disarm()
            drain_net([b, c])

            assert sb.get_text() == "hello w0w1w2w3"
            assert c.get_channel("s").get_text() == "busy!"
            # Server-side device replica converged identically — nothing
            # in the parked burst was lost, duplicated, or reordered:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                # This poll crosses threads against the live server loop
                # (the residency ticker is still sweeping): a donated
                # pool state can transiently vanish mid-readback. Retry
                # inside the deadline; the asserts below are the real
                # check.
                try:
                    if svc.device.stats()["parked_rows"] == 0 and (
                        svc.device_text("wakedoc", "s")
                        == "hello w0w1w2w3"
                    ):
                        break
                except RuntimeError:
                    pass
                time.sleep(0.05)
            assert svc.device_text("wakedoc", "s") == "hello w0w1w2w3"
            assert svc.device.stats()["parked_rows"] == 0
            rs = svc.device.residency.stats()
            assert rs["wakes"].get("retry", 0) >= 1, "the faulted attempt"
            assert rs["wakes"].get("ok", 0) >= 1, "the recovery"
            # The sequenced stream itself is gapless and strictly
            # ordered — the pending queue preserved the total order:
            seqs = [
                m.sequence_number
                for m in svc.get_deltas("wakedoc", from_seq=0)
            ]
            assert seqs == sorted(seqs)
            assert len(seqs) == len(set(seqs)), "no duplicated tickets"
            ops = [
                m
                for m in svc.get_deltas("wakedoc", from_seq=0)
                if m.type == MessageType.OPERATION
            ]
            texts = [
                m.contents.get("contents", {}).get("text")
                for m in ops
                if isinstance(m.contents, dict)
            ]
            want = ["hello ", "w0", "w1", "w2", "w3"]
            got = [t for t in texts if t in want]
            assert got == want, "burst must sequence in submit order"
        finally:
            faults.disarm()
            srv.stop()
