"""CLI: ``python -m tools.graftlint [--check] [paths...]``.

Modes:
  (default / --check)    run all passes, subtract the committed baseline,
                         exit 1 on any finding (the CI gate) — including
                         stale-pragma findings: a reasoned pragma whose
                         finding no longer fires must be deleted
  --regen-fingerprints   accept intentional codec changes: rewrite
                         api-report/wire_fingerprints.json, bumping the
                         version of every drifted module
  --write-baseline       snapshot current findings into the baseline
                         (burn-down staging INSIDE a PR only — the
                         committed baseline must be empty at merge)
  --passes a,b           restrict to a subset of pass ids
  --no-baseline          report everything, ignoring the baseline
  --stale-pragmas        report ONLY stale-pragma findings (the sweep
                         mode — the default --check already fails on
                         them)
  --format text|json|sarif
                         machine-readable findings, so the CI lint job
                         annotates the PR diff instead of only failing
                         the build
  --timings              emit per-pass wall seconds (the CI lint job
                         runs with this so a slow pass is visible in
                         the job log, not just as a slower gate)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.graftlint import config, core
from tools.graftlint.passes import ALL_PASSES, wire_drift


def _as_json(findings, stale, timings=None) -> dict:
    doc = {
        "tool": "graftlint",
        "findings": [
            {
                "rule": f.rule,
                "path": f.path,
                "line": f.line,
                "col": f.col,
                "message": f.message,
            }
            for f in findings
        ],
        "stale_baseline_entries": list(stale),
    }
    if timings is not None:
        doc["pass_seconds"] = {
            k: round(v, 4) for k, v in sorted(timings.items())
        }
    return doc


def _as_sarif(findings) -> dict:
    """SARIF 2.1.0 — the minimal shape GitHub's code-scanning upload and
    PR annotators consume."""
    rules = sorted({f.rule for f in findings})
    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftlint",
                        "informationUri": "tools/graftlint/README.md",
                        "rules": [{"id": r} for r in rules],
                    }
                },
                "results": [
                    {
                        "ruleId": f.rule,
                        "level": "error",
                        "message": {"text": f.message},
                        "locations": [
                            {
                                "physicalLocation": {
                                    "artifactLocation": {"uri": f.path},
                                    "region": {
                                        "startLine": f.line,
                                        "startColumn": f.col,
                                    },
                                }
                            }
                        ],
                    }
                    for f in findings
                ],
            }
        ],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.graftlint")
    ap.add_argument("paths", nargs="*", help="repo-relative file filters")
    ap.add_argument("--check", action="store_true",
                    help="run all passes (the default; explicit for CI)")
    ap.add_argument("--passes", default=None,
                    help="comma-separated pass ids "
                         f"({', '.join(p.id for p in ALL_PASSES)})")
    ap.add_argument("--regen-fingerprints", action="store_true",
                    help="rewrite the wire fingerprint lock (+version "
                         "bumps) for intentional codec changes")
    ap.add_argument("--write-baseline", action="store_true",
                    help="snapshot current findings into the baseline")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the committed baseline")
    ap.add_argument("--stale-pragmas", action="store_true",
                    help="report only stale-pragma findings (sweep mode)")
    ap.add_argument("--format", default="text",
                    choices=("text", "json", "sarif"),
                    help="findings output format (json/sarif for CI "
                         "diff annotation)")
    ap.add_argument("--timings", action="store_true",
                    help="emit per-pass wall seconds")
    args = ap.parse_args(argv)

    root = config.REPO_ROOT
    if args.regen_fingerprints:
        changed = wire_drift.regenerate(root)
        if changed:
            print("graftlint: fingerprints regenerated for: "
                  + ", ".join(changed))
        else:
            print("graftlint: fingerprints already current")
        return 0

    passes = args.passes.split(",") if args.passes else None
    known = {p.id for p in ALL_PASSES}
    if passes and not set(passes) <= known:
        print(f"graftlint: unknown pass(es) {set(passes) - known}",
              file=sys.stderr)
        return 2

    if args.write_baseline:
        findings, _ = core.run(root, passes=passes, paths=args.paths or None,
                               use_baseline=False,
                               check_stale_pragmas=False)
        path = os.path.join(root, config.BASELINE_FILE)
        with open(path, "w") as f:
            json.dump([fi.baseline_key() for fi in findings], f, indent=1)
            f.write("\n")
        print(f"graftlint: baselined {len(findings)} finding(s) — the "
              "committed baseline must be empty at merge")
        return 0

    timings: dict = {}
    findings, stale = core.run(
        root,
        passes=passes,
        paths=args.paths or None,
        use_baseline=not args.no_baseline,
        timings=timings if args.timings else None,
    )
    if args.stale_pragmas:
        findings = [f for f in findings if f.rule == "stale-pragma"]
        stale = []

    if args.format == "json":
        print(json.dumps(
            _as_json(findings, stale,
                     timings if args.timings else None),
            indent=1,
        ))
        return 1 if findings or stale else 0
    if args.format == "sarif":
        print(json.dumps(_as_sarif(findings), indent=1))
        return 1 if findings or stale else 0

    for f in findings:
        print(f.render())
    for e in stale:
        print(
            f"{e['path']}: [baseline] stale baseline entry for "
            f"{e['rule']!r} ({e['source_line'][:60]!r}) — remove it from "
            f"{config.BASELINE_FILE}"
        )
    if args.timings:
        for pid in sorted(timings):
            print(f"graftlint: pass {pid}: {timings[pid]:.3f}s")
    n = len(findings) + len(stale)
    if n:
        print(f"graftlint: {len(findings)} finding(s), "
              f"{len(stale)} stale baseline entrie(s)")
        return 1
    print(f"graftlint: clean ({len(ALL_PASSES)} passes, empty baseline)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
