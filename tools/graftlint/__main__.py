"""CLI: ``python -m tools.graftlint [--check] [paths...]``.

Modes:
  (default / --check)    run all passes, subtract the committed baseline,
                         exit 1 on any finding (the CI gate)
  --regen-fingerprints   accept intentional codec changes: rewrite
                         api-report/wire_fingerprints.json, bumping the
                         version of every drifted module
  --write-baseline       snapshot current findings into the baseline
                         (burn-down staging INSIDE a PR only — the
                         committed baseline must be empty at merge)
  --passes a,b           restrict to a subset of pass ids
  --no-baseline          report everything, ignoring the baseline
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.graftlint import config, core
from tools.graftlint.passes import ALL_PASSES, wire_drift


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.graftlint")
    ap.add_argument("paths", nargs="*", help="repo-relative file filters")
    ap.add_argument("--check", action="store_true",
                    help="run all passes (the default; explicit for CI)")
    ap.add_argument("--passes", default=None,
                    help="comma-separated pass ids "
                         f"({', '.join(p.id for p in ALL_PASSES)})")
    ap.add_argument("--regen-fingerprints", action="store_true",
                    help="rewrite the wire fingerprint lock (+version "
                         "bumps) for intentional codec changes")
    ap.add_argument("--write-baseline", action="store_true",
                    help="snapshot current findings into the baseline")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the committed baseline")
    args = ap.parse_args(argv)

    root = config.REPO_ROOT
    if args.regen_fingerprints:
        changed = wire_drift.regenerate(root)
        if changed:
            print("graftlint: fingerprints regenerated for: "
                  + ", ".join(changed))
        else:
            print("graftlint: fingerprints already current")
        return 0

    passes = args.passes.split(",") if args.passes else None
    known = {p.id for p in ALL_PASSES}
    if passes and not set(passes) <= known:
        print(f"graftlint: unknown pass(es) {set(passes) - known}",
              file=sys.stderr)
        return 2

    if args.write_baseline:
        findings, _ = core.run(root, passes=passes, paths=args.paths or None,
                               use_baseline=False)
        path = os.path.join(root, config.BASELINE_FILE)
        with open(path, "w") as f:
            json.dump([fi.baseline_key() for fi in findings], f, indent=1)
            f.write("\n")
        print(f"graftlint: baselined {len(findings)} finding(s) — the "
              "committed baseline must be empty at merge")
        return 0

    findings, stale = core.run(
        root,
        passes=passes,
        paths=args.paths or None,
        use_baseline=not args.no_baseline,
    )
    for f in findings:
        print(f.render())
    for e in stale:
        print(
            f"{e['path']}: [baseline] stale baseline entry for "
            f"{e['rule']!r} ({e['source_line'][:60]!r}) — remove it from "
            f"{config.BASELINE_FILE}"
        )
    n = len(findings) + len(stale)
    if n:
        print(f"graftlint: {len(findings)} finding(s), "
              f"{len(stale)} stale baseline entrie(s)")
        return 1
    print(f"graftlint: clean ({len(ALL_PASSES)} passes, empty baseline)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
