"""wire-drift: codec field/layout fingerprints locked against a
committed file.

ROADMAP calls the accreting wire formats (planar wire, pack blobs, int8
lanes, frames, move wire) "the biggest structural risk": formats gain
fields every round and nothing mechanical noticed when one changed. This
pass extracts a STATIC fingerprint from each codec module's AST —

- module-level layout constants (``_RAW_MAGIC``, ``_T_*`` tags, ``F_*``
  field indices, ``MARK_KINDS``, ``SEGMENT_LANES``, ...): name → literal
  value; non-literal constants (e.g. codec type registries) record their
  sorted keys/element names;
- every ``struct.pack``/``unpack``/``unpack_from``/``pack_into``/
  ``calcsize``/``Struct`` format string (byte layout in one token);
- ``__slots__`` tuples and ``@dataclass`` field orders (wire-visible
  attribute order);

— and compares it against ``api-report/wire_fingerprints.json``. Any
drift fails ``--check``. An INTENTIONAL format change is accepted by
``python -m tools.graftlint --regen-fingerprints``, which rewrites the
fingerprint and bumps that module's version — so the committed diff
shows the bump, review sees it, and the matching golden fixture
(e.g. ``tests/goldens/golden_move_wire.json``) must move in the same PR.
There is no inline pragma for this pass: the lock file IS the
suppression, and it leaves an audit trail.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from typing import Dict, Iterator, List, Tuple

from tools.graftlint import config
from tools.graftlint.core import Finding, ModuleSource

_CONST_NAME = re.compile(r"^_?[A-Z][A-Z0-9_]*$")
_STRUCT_FNS = ("pack", "unpack", "unpack_from", "pack_into", "calcsize",
               "Struct", "iter_unpack")


def _const_value(node: ast.AST) -> object:
    """Literal repr for a constant's value; containers of non-literals
    degrade to their stable shape (dict keys / element names)."""
    try:
        return repr(ast.literal_eval(node))
    except ValueError:
        pass
    if isinstance(node, ast.Dict):
        keys = []
        for k in node.keys:
            try:
                keys.append(repr(ast.literal_eval(k)))
            except ValueError:
                keys.append(ast.unparse(k) if k is not None else "**")
        return {"keys": sorted(keys)}
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return {"elts": [ast.unparse(e) for e in node.elts]}
    return {"expr": ast.unparse(node)}


def fingerprint_source(text: str, filename: str = "<codec>") -> dict:
    """The static wire fingerprint of one codec module's source."""
    tree = ast.parse(text, filename=filename)
    constants: Dict[str, object] = {}
    for stmt in tree.body:
        targets: List[ast.AST] = []
        value = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if value is None:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and _CONST_NAME.match(t.id):
                constants[t.id] = _const_value(value)
    struct_formats: List[str] = []
    slots: Dict[str, object] = {}
    dataclass_fields: Dict[str, List[str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in _STRUCT_FNS
                and isinstance(f.value, ast.Name)
                and f.value.id == "struct"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                struct_formats.append(node.args[0].value)
        elif isinstance(node, ast.ClassDef):
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and any(
                        isinstance(t, ast.Name) and t.id == "__slots__"
                        for t in stmt.targets
                    )
                ):
                    slots[node.name] = _const_value(stmt.value)
            if any(
                (isinstance(d, ast.Name) and d.id == "dataclass")
                or (
                    isinstance(d, ast.Call)
                    and isinstance(d.func, ast.Name)
                    and d.func.id == "dataclass"
                )
                or (isinstance(d, ast.Attribute) and d.attr == "dataclass")
                for d in node.decorator_list
            ):
                dataclass_fields[node.name] = [
                    s.target.id
                    for s in node.body
                    if isinstance(s, ast.AnnAssign)
                    and isinstance(s.target, ast.Name)
                ]
    return {
        "constants": constants,
        "struct_formats": sorted(struct_formats),
        "slots": slots,
        "dataclass_fields": dataclass_fields,
    }


def digest(fp: dict) -> str:
    return hashlib.sha256(
        json.dumps(fp, sort_keys=True).encode()
    ).hexdigest()


def load_lock(root: str) -> dict:
    path = os.path.join(root, config.WIRE_LOCK_FILE)
    if not os.path.exists(path):
        return {"modules": {}}
    with open(path) as f:
        return json.load(f)


def _diff_keys(old: dict, new: dict) -> List[str]:
    out = []
    for section in ("constants", "struct_formats", "slots",
                    "dataclass_fields"):
        a, b = old.get(section), new.get(section)
        if a == b:
            continue
        if isinstance(a, dict) and isinstance(b, dict):
            changed = sorted(
                k
                for k in set(a) | set(b)
                if a.get(k) != b.get(k)
            )
            out.append(f"{section}: {', '.join(changed)}")
        else:
            out.append(section)
    return out


def regenerate(root: str) -> List[str]:
    """Recompute every configured module's fingerprint; bump versions for
    changed ones; write the lock file. Returns the changed module list."""
    lock = load_lock(root)
    modules = lock.get("modules", {})
    changed = []
    for rel in config.CODEC_MODULES:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            continue  # scope() skips absent modules too (fixture trees)
        with open(path, encoding="utf-8") as f:
            fp = fingerprint_source(f.read(), rel)
        d = digest(fp)
        prev = modules.get(rel)
        if prev is None:
            modules[rel] = {"version": 1, "digest": d, "fingerprint": fp}
            changed.append(rel)
        elif prev["digest"] != d:
            modules[rel] = {
                "version": prev["version"] + 1,
                "digest": d,
                "fingerprint": fp,
            }
            changed.append(rel)
    for rel in list(modules):
        if rel not in config.CODEC_MODULES:
            del modules[rel]
            changed.append(rel)
    out = {
        "_comment": (
            "Committed wire-format fingerprints (graftlint wire-drift "
            "gate). Regenerate ONLY for intentional format changes: "
            "python -m tools.graftlint --regen-fingerprints — the "
            "version bump this writes is what review keys on, and the "
            "matching golden (e.g. tests/goldens/*) must move in the "
            "same PR."
        ),
        "modules": {k: modules[k] for k in sorted(modules)},
    }
    path = os.path.join(root, config.WIRE_LOCK_FILE)
    with open(path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    return changed


class WireDriftPass:
    id = "wire-drift"

    def scope(self, root: str) -> List[str]:
        # Configured explicitly — codec modules, not a glob.
        return [
            rel
            for rel in config.CODEC_MODULES
            if os.path.exists(os.path.join(root, rel))
        ]

    def run(self, src: ModuleSource) -> Iterator[Tuple[Finding, ast.AST]]:
        root = config.REPO_ROOT
        # src.abspath is under some root; derive it so tests can point the
        # pass at fixture trees.
        if src.abspath.endswith(src.path.replace("/", os.sep)):
            root = src.abspath[: -len(src.path) - 1] or root
        lock = load_lock(root).get("modules", {})
        fp = fingerprint_source(src.text, src.path)
        entry = lock.get(src.path)
        anchor = src.tree.body[0] if getattr(src.tree, "body", None) else src.tree
        if entry is None:
            yield (
                src.finding(
                    self.id,
                    anchor,
                    "codec module has no committed wire fingerprint — "
                    "run `python -m tools.graftlint --regen-fingerprints` "
                    "and commit api-report/wire_fingerprints.json",
                ),
                anchor,
            )
            return
        if entry["digest"] != digest(fp):
            diffs = _diff_keys(entry.get("fingerprint", {}), fp)
            yield (
                src.finding(
                    self.id,
                    anchor,
                    "wire-format fingerprint drift ("
                    + "; ".join(diffs or ["content"])
                    + f") vs locked v{entry['version']} — if the format "
                    "change is intentional, run `python -m tools.graftlint "
                    "--regen-fingerprints` (bumps the version) and "
                    "regenerate the matching golden in the same PR",
                ),
                anchor,
            )
