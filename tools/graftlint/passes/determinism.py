"""determinism: iteration-order hazards in merge/sequencing modules.

Every replica folds the same totally-ordered op stream into state; the
paper's guarantee is that the folds are IDENTICAL. Python hands out two
footguns that break this silently:

- ``set``/``frozenset`` iteration order depends on insertion history and
  hash seeding — two replicas that built the same set along different
  paths iterate it differently. Flagged wherever a set-typed value is
  iterated (``for``, comprehensions, ``list()``/``tuple()``/
  ``enumerate()``/``join()``/``map()``/``filter()``); order-independent
  folds (``sorted``/``min``/``max``/``sum``/``any``/``all``/``len``) are
  exempt.
- ``id()`` is a per-process address: any ordering keyed on it
  (``sorted(key=id)``, ``{id(x): ...}``, ``{id(x) for x}``) diverges
  across replicas by construction. ``hash()`` sort keys are flagged for
  the same reason (str hashes are salted per process).

Set-typedness is inferred locally (assignments from ``set()``/
``frozenset()``/set literals/set comprehensions, and ``self.X`` attrs
assigned a set anywhere in the same class). Intentional uses —
membership-only structures whose order is never observed — carry
``# graftlint: nondet(<reason>)``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.graftlint import config
from tools.graftlint.core import Finding, ModuleSource, scope_files

# Consumers whose result ORDER exposes the iterable's order.
_ORDER_SENSITIVE = ("list", "tuple", "enumerate", "map", "filter", "iter",
                    "reversed")
# Order-independent folds: iterating a set through these is sound.
_ORDER_FREE = ("sorted", "min", "max", "sum", "any", "all", "len",
               "frozenset", "set")


def _is_set_expr(node: ast.AST, env: Dict[str, bool],
                 attrs: Set[str]) -> bool:
    """Conservative set-typedness: literal constructors, known locals,
    and known self attributes."""
    if isinstance(node, ast.Set) or isinstance(node, ast.SetComp):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id in ("set", "frozenset"):
            return True
    if isinstance(node, ast.Name):
        return env.get(node.id, False)
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr in attrs
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.BitAnd, ast.BitOr, ast.BitXor, ast.Sub)
    ):
        # set algebra yields sets
        return _is_set_expr(node.left, env, attrs) or _is_set_expr(
            node.right, env, attrs
        )
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr in (
            "union", "intersection", "difference", "symmetric_difference",
            "copy",
        ):
            return _is_set_expr(node.func.value, env, attrs)
    return False


def _set_attrs_of_classes(tree: ast.AST) -> Dict[str, Set[str]]:
    """class name -> self attributes assigned a set anywhere in it."""
    out: Dict[str, Set[str]] = {}
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        attrs: Set[str] = set()
        for node in ast.walk(cls):
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is None or not _is_set_expr(value, {}, set()):
                continue
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    attrs.add(t.attr)
        out[cls.name] = attrs
    return out


def _contains_id_call(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Name)
            and sub.func.id in ("id", "hash")
        ):
            return True
        if isinstance(sub, ast.Name) and sub.id in ("id", "hash"):
            # bare `key=id`
            return True
    return False


class DeterminismPass:
    id = "determinism"

    def scope(self, root: str) -> List[str]:
        return scope_files(root, config.MERGE_PATH_SCOPE)

    def run(self, src: ModuleSource) -> Iterator[Tuple[Finding, ast.AST]]:
        class_attrs = _set_attrs_of_classes(src.tree)
        yield from self._walk_scope(
            src, src.tree.body, env={}, attrs=set(), class_attrs=class_attrs
        )

    def _walk_scope(
        self,
        src: ModuleSource,
        body: List[ast.stmt],
        env: Dict[str, bool],
        attrs: Set[str],
        class_attrs: Dict[str, Set[str]],
    ) -> Iterator[Tuple[Finding, ast.AST]]:
        for stmt in body:
            if isinstance(stmt, ast.ClassDef):
                yield from self._walk_scope(
                    src,
                    stmt.body,
                    {},
                    class_attrs.get(stmt.name, set()),
                    class_attrs,
                )
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._walk_scope(
                    src, stmt.body, {}, attrs, class_attrs
                )
                continue
            yield from self._check_stmt(src, stmt, env, attrs)
            # order matters: bindings update after the check
            if isinstance(stmt, ast.Assign):
                is_set = _is_set_expr(stmt.value, env, attrs)
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        env[t.id] = is_set
            elif isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                if stmt.value is not None:
                    env[stmt.target.id] = _is_set_expr(
                        stmt.value, env, attrs
                    )
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                yield from self._walk_scope(
                    src, stmt.body, env, attrs, class_attrs
                )
                yield from self._walk_scope(
                    src, stmt.orelse, env, attrs, class_attrs
                )
            elif isinstance(stmt, (ast.If, ast.While)):
                yield from self._walk_scope(
                    src, stmt.body, env, attrs, class_attrs
                )
                yield from self._walk_scope(
                    src, stmt.orelse, env, attrs, class_attrs
                )
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from self._walk_scope(
                    src, stmt.body, env, attrs, class_attrs
                )
            elif isinstance(stmt, ast.Try):
                for blk in (stmt.body, stmt.orelse, stmt.finalbody):
                    yield from self._walk_scope(
                        src, blk, env, attrs, class_attrs
                    )
                for h in stmt.handlers:
                    yield from self._walk_scope(
                        src, h.body, env, attrs, class_attrs
                    )

    def _check_stmt(
        self,
        src: ModuleSource,
        stmt: ast.stmt,
        env: Dict[str, bool],
        attrs: Set[str],
    ) -> Iterator[Tuple[Finding, ast.AST]]:
        # for-loop over a set (header only; bodies re-enter _walk_scope)
        if isinstance(stmt, (ast.For, ast.AsyncFor)) and _is_set_expr(
            stmt.iter, env, attrs
        ):
            yield (
                src.finding(
                    self.id,
                    stmt.iter,
                    f"iterating set-typed {ast.unparse(stmt.iter)!r} has "
                    "no deterministic order — replicas diverge; iterate "
                    "sorted(...) with a total-order key or annotate "
                    "`# graftlint: nondet(<reason>)`",
                ),
                stmt,
            )
        roots: List[ast.AST]
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            # Body statements re-enter _walk_scope; the header expression
            # still needs the consumer checks (`for k in list(ids):` hides
            # the set inside a call the direct check above can't see).
            roots = [stmt.iter]
        elif isinstance(stmt, (ast.If, ast.While)):
            roots = [stmt.test]
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            roots = [i.context_expr for i in stmt.items]
        elif isinstance(stmt, ast.Try):
            roots = []
        else:
            roots = [stmt]
        for root in roots:
            for node in ast.walk(root):
                yield from self._check_expr_node(src, node, stmt, env, attrs)

    def _check_expr_node(
        self,
        src: ModuleSource,
        node: ast.AST,
        stmt: ast.stmt,
        env: Dict[str, bool],
        attrs: Set[str],
    ) -> Iterator[Tuple[Finding, ast.AST]]:
        # comprehension over a set
        if isinstance(
            node, (ast.ListComp, ast.GeneratorExp, ast.SetComp, ast.DictComp)
        ):
            for gen in node.generators:
                if isinstance(node, ast.SetComp):
                    continue  # building a set: order of construction moot
                if _is_set_expr(gen.iter, env, attrs):
                    yield (
                        src.finding(
                            self.id,
                            gen.iter,
                            "comprehension over set-typed "
                            f"{ast.unparse(gen.iter)!r} has no "
                            "deterministic order — iterate sorted(...) "
                            "or annotate `# graftlint: nondet(<reason>)`",
                        ),
                        stmt,
                    )
            # id()-keyed set/dict comprehensions
            if isinstance(node, ast.SetComp) and _contains_id_call(node.elt):
                yield (
                    src.finding(
                        self.id,
                        node,
                        "id()-keyed set: process-local addresses can "
                        "never order consistently across replicas — key "
                        "on a stable identity or annotate "
                        "`# graftlint: nondet(<reason>)`",
                    ),
                    stmt,
                )
            if isinstance(node, ast.DictComp) and _contains_id_call(node.key):
                yield (
                    src.finding(
                        self.id,
                        node,
                        "id()-keyed dict: process-local addresses can "
                        "never order consistently across replicas — key "
                        "on a stable identity or annotate "
                        "`# graftlint: nondet(<reason>)`",
                    ),
                    stmt,
                )
            return
        if isinstance(node, ast.Dict):
            if any(k is not None and _contains_id_call(k) for k in node.keys):
                yield (
                    src.finding(
                        self.id,
                        node,
                        "id()-keyed dict literal: process-local addresses "
                        "can never order consistently across replicas — "
                        "key on a stable identity or annotate "
                        "`# graftlint: nondet(<reason>)`",
                    ),
                    stmt,
                )
            return
        if not isinstance(node, ast.Call):
            return
        f = node.func
        # order-sensitive consumers of sets (ANY positional arg: the set
        # sits at args[0] for enumerate(ids, 1), args[1] for map(f, ids))
        if isinstance(f, ast.Name) and f.id in _ORDER_SENSITIVE:
            if any(_is_set_expr(a, env, attrs) for a in node.args):
                yield (
                    src.finding(
                        self.id,
                        node,
                        f"{f.id}() over a set exposes nondeterministic "
                        "order — wrap in sorted(...) with a total-order "
                        "key or annotate `# graftlint: nondet(<reason>)`",
                    ),
                    stmt,
                )
        # "sep".join(set)
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "join"
            and node.args
            and _is_set_expr(node.args[0], env, attrs)
        ):
            yield (
                src.finding(
                    self.id,
                    node,
                    "join() over a set concatenates in nondeterministic "
                    "order — sort first",
                ),
                stmt,
            )
        # id()/hash() sort keys
        is_sort = (
            isinstance(f, ast.Name) and f.id in ("sorted", "min", "max")
        ) or (isinstance(f, ast.Attribute) and f.attr == "sort")
        if is_sort:
            for kw in node.keywords:
                if kw.arg == "key" and _contains_id_call(kw.value):
                    yield (
                        src.finding(
                            self.id,
                            node,
                            "sort keyed on id()/hash(): process-local "
                            "values break the total order replicas must "
                            "share — use a sequenced/stable key",
                        ),
                        stmt,
                    )
