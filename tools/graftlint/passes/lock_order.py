"""lock-order: static lock-acquisition discipline for telemetry/service.

The r16 e2e drives caught the repo's nastiest class of bug so far: the
profiler's gc callback took the metrics/ring locks, and since gc fires
mid-allocation on WHATEVER thread triggered collection — including one
already inside a locked ``observe()`` — the event loop deadlocked
against itself (intermittent /metrics hangs). The fix made the callback
lock-free **by contract** (it only buffers; ``drain_gc_events`` folds).
This pass makes that contract — and the wider ordering discipline it is
an instance of — machine-enforced:

- **Lock graph + cycle detection**: every ``with <lock>`` acquisition
  (attributes named ``lock``/``_lock``/``*_lock``) is a node; acquiring
  M while holding L adds the edge L→M — directly, through same-module
  calls made under the lock, and through the known cross-module lock
  calls (metric ``inc``/``observe`` take the per-metric lock, registry
  registration takes the registry lock, ``journal.record``/
  ``profiler.record`` take their ring locks). Any cycle in the combined
  graph across the scope is a deadlock waiting for the right interleave
  — reported once per cycle, with the edge list.
- **Lock-free contexts**: functions registered in ``gc.callbacks`` or
  as ``signal.signal`` handlers must acquire NO lock, transitively —
  the exact r16 shape. The acceptance mechanism for a lock-needing
  collector hook is the buffer-and-drain split, not a pragma.
- **Render paths** (``config.RENDER_PATHS`` — the exposition functions
  scrape threads call): may take ONE lock at a time (the snapshot-
  under-lock-render-outside pattern); acquiring a second lock while
  holding one is the nested-hold shape that turns a scrape into a
  deadlock participant.

Cycle findings have NO pragma — like wire-drift, the acceptance
mechanism is structural (order the locks, or split the hold). The
per-file findings (nested render hold, forbidden-context acquisition)
accept a reasoned ``# graftlint: lockorder(<reason>)`` for audited
exceptions.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tools.graftlint import config
from tools.graftlint.core import Finding, ModuleSource, scope_files


def _lock_attr_name(node: ast.AST) -> Optional[str]:
    """The lock attribute name when ``node`` is a recognized lock
    expression (``self._lock``, ``hist._lock``, ``LOCK``...)."""
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    else:
        return None
    low = name.lower()
    if low in config.LOCK_NAMES or low.endswith("_lock"):
        return name
    return None


class _FnLocks:
    """Per-function lock facts: direct acquisitions (with the held set
    at that point), same-module calls made while holding, and
    cross-module known-lock calls."""

    __slots__ = ("name", "node", "acquires", "calls", "closure")

    def __init__(self, name: str, node: ast.AST):
        self.name = name
        self.node = node
        # (lock id, node, anchor stmt, held tuple at acquisition)
        self.acquires: List[Tuple[str, ast.AST, ast.stmt, Tuple[str, ...]]] = []
        # (callee bare name, node, anchor stmt, held tuple)
        self.calls: List[Tuple[str, ast.AST, ast.stmt, Tuple[str, ...]]] = []
        self.closure: Set[str] = set()  # locks this fn may acquire


class LockOrderPass:
    id = "lock-order"

    def __init__(self) -> None:
        # Cross-file state for the cycle check (finalize).
        self._edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self._edge_order: List[Tuple[str, str]] = []

    def scope(self, root: str) -> List[str]:
        self._edges = {}
        self._edge_order = []
        return scope_files(root, config.LOCK_SCOPE)

    # -- lock identity ---------------------------------------------------------

    def _lock_id(
        self, node: ast.AST, cls: Optional[str], src: ModuleSource
    ) -> Optional[str]:
        attr = _lock_attr_name(node)
        if attr is None:
            return None
        if isinstance(node, ast.Attribute):
            recv = node.value
            if isinstance(recv, ast.Name) and recv.id in ("self", "cls"):
                owner = cls or src.path
            else:
                owner = ast.unparse(recv)
        else:
            owner = src.path
        return f"{owner}.{attr}"

    # -- per-function walk -----------------------------------------------------

    def _collect(
        self, src: ModuleSource
    ) -> Tuple[Dict[str, _FnLocks], List[str], List[str]]:
        """(functions, gc-callback names, signal-handler names)."""
        fns: Dict[str, _FnLocks] = {}
        gc_cbs: List[str] = []
        sig_handlers: List[str] = []

        def visit_fn(fn: ast.AST, cls: Optional[str]) -> None:
            info = fns.setdefault(fn.name, _FnLocks(fn.name, fn))
            self._walk_body(src, fn.body, cls, (), info)

        def visit_scope(body, cls: Optional[str]) -> None:
            for stmt in body:
                if isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    visit_fn(stmt, cls)
                    visit_scope(stmt.body, cls)
                elif isinstance(stmt, ast.ClassDef):
                    visit_scope(stmt.body, stmt.name)

        visit_scope(src.tree.body, None)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            # gc.callbacks.append(fn)
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "append"
                and isinstance(f.value, ast.Attribute)
                and f.value.attr == "callbacks"
                and isinstance(f.value.value, ast.Name)
                and f.value.value.id == "gc"
                and node.args
            ):
                name = _term(node.args[0])
                if name:
                    gc_cbs.append(name)
            # signal.signal(sig, fn)
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "signal"
                and isinstance(f.value, ast.Name)
                and f.value.id == "signal"
                and len(node.args) == 2
            ):
                name = _term(node.args[1])
                if name:
                    sig_handlers.append(name)
        return fns, gc_cbs, sig_handlers

    def _walk_body(
        self,
        src: ModuleSource,
        body: Sequence[ast.stmt],
        cls: Optional[str],
        held: Tuple[str, ...],
        info: _FnLocks,
    ) -> None:
        for stmt in body:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue  # visited as their own functions/scopes
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                now = held
                for item in stmt.items:
                    lock = self._lock_id(item.context_expr, cls, src)
                    if lock is not None:
                        info.acquires.append((lock, item.context_expr, stmt, now))
                        now = now + (lock,)
                    else:
                        self._scan_expr(
                            src, item.context_expr, stmt, cls, now, info
                        )
                self._walk_body(src, stmt.body, cls, now, info)
                continue
            # Scan this statement's own expressions, then recurse into
            # compound-statement bodies with the same held set.
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_expr(src, stmt.iter, stmt, cls, held, info)
                self._walk_body(src, stmt.body, cls, held, info)
                self._walk_body(src, stmt.orelse, cls, held, info)
            elif isinstance(stmt, (ast.If, ast.While)):
                self._scan_expr(src, stmt.test, stmt, cls, held, info)
                self._walk_body(src, stmt.body, cls, held, info)
                self._walk_body(src, stmt.orelse, cls, held, info)
            elif isinstance(stmt, ast.Try):
                for blk in (stmt.body, stmt.orelse, stmt.finalbody):
                    self._walk_body(src, blk, cls, held, info)
                for h in stmt.handlers:
                    self._walk_body(src, h.body, cls, held, info)
            else:
                self._scan_expr(src, stmt, stmt, cls, held, info)

    def _scan_expr(
        self,
        src: ModuleSource,
        root: ast.AST,
        stmt: ast.stmt,
        cls: Optional[str],
        held: Tuple[str, ...],
        info: _FnLocks,
    ) -> None:
        for node in ast.walk(root):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = _term(f)
            # Explicit .acquire() on a lock.
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "acquire"
            ):
                lock = self._lock_id(f.value, cls, src)
                if lock is not None:
                    info.acquires.append((lock, node, stmt, held))
                    continue
            # Known cross-module lock calls.
            known = self._known_lock(node)
            if known is not None:
                info.acquires.append((known, node, stmt, held))
                continue
            if isinstance(f, (ast.Name, ast.Attribute)) and name:
                info.calls.append((name, node, stmt, held))

    @staticmethod
    def _known_lock(node: ast.Call) -> Optional[str]:
        f = node.func
        if not isinstance(f, ast.Attribute):
            return None
        if f.attr == "record":
            recv = _term(f.value)
            if recv in config.RECORD_LOCKS:
                return config.RECORD_LOCKS[recv]
            return None
        if f.attr in config.KNOWN_LOCK_CALLS:
            recv = _term(f.value)
            if f.attr in ("inc", "observe"):
                return config.KNOWN_LOCK_CALLS[f.attr]
            # counter/gauge/histogram registration: only on registry
            # receivers (reg/registry/REGISTRY).
            if recv.lower() in ("reg", "registry"):
                return config.KNOWN_LOCK_CALLS[f.attr]
        return None

    # -- pass entry ------------------------------------------------------------

    def run(self, src: ModuleSource) -> Iterator[Tuple[Finding, ast.AST]]:
        fns, gc_cbs, sig_handlers = self._collect(src)

        # Per-function acquire closures (fixed point over local calls).
        for info in fns.values():
            info.closure = {lock for lock, *_ in info.acquires}
        changed = True
        while changed:
            changed = False
            for info in fns.values():
                for callee, *_ in info.calls:
                    sub = fns.get(callee)
                    if sub is None:
                        continue
                    if not sub.closure <= info.closure:
                        info.closure |= sub.closure
                        changed = True

        # Lock-order edges: held L × acquired M (direct and via calls).
        for info in fns.values():
            for lock, node, stmt, held in info.acquires:
                for h in held:
                    if h != lock:
                        self._add_edge(h, lock, src, node)
            for callee, node, stmt, held in info.calls:
                if not held:
                    continue
                sub = fns.get(callee)
                if sub is None:
                    continue
                for h in held:
                    for lock in sub.closure:
                        if h != lock:
                            self._add_edge(h, lock, src, node)

        # Self-deadlock: re-acquiring a non-reentrant lock already held.
        for info in fns.values():
            for lock, node, stmt, held in info.acquires:
                if lock in held:
                    yield (
                        src.finding(
                            self.id,
                            node,
                            f"lock {lock!r} acquired while already held "
                            "— a non-reentrant self-deadlock",
                        ),
                        stmt,
                    )

        # Lock-free contexts: gc callbacks and signal handlers.
        for kind, names in (
            ("gc.callbacks", gc_cbs),
            ("signal handler", sig_handlers),
        ):
            for name in names:
                info = fns.get(name)
                if info is None:
                    continue
                locks = sorted(info.closure)
                if locks:
                    yield (
                        src.finding(
                            self.id,
                            info.node,
                            f"{kind} {name!r} may acquire "
                            f"{', '.join(locks)} — {kind.split()[0]} "
                            "contexts run mid-allocation on arbitrary "
                            "threads and must be lock-free by contract "
                            "(buffer and drain instead; "
                            "docs/failure-semantics.md, the r16 "
                            "gc-callback deadlock)",
                        ),
                        info.node,
                    )

        # Render paths: one lock at a time (snapshot under the lock,
        # render outside it).
        for name in config.RENDER_PATHS.get(src.path, ()):
            info = fns.get(name)
            if info is None:
                continue
            for lock, node, stmt, held in info.acquires:
                if held and lock not in held:
                    yield (
                        src.finding(
                            self.id,
                            node,
                            f"render path {name}() acquires {lock!r} "
                            f"while holding {held[-1]!r} — render paths "
                            "hold ONE lock at a time (snapshot under "
                            "the lock, render outside it)",
                        ),
                        stmt,
                    )
            for callee, node, stmt, held in info.calls:
                sub = fns.get(callee)
                if sub is None or not held:
                    continue
                nested = sorted(sub.closure - set(held))
                if nested:
                    yield (
                        src.finding(
                            self.id,
                            node,
                            f"render path {name}() calls {callee}() "
                            f"while holding {held[-1]!r} (it may acquire "
                            f"{', '.join(nested)}) — render paths hold "
                            "ONE lock at a time",
                        ),
                        stmt,
                    )

    def _add_edge(
        self, a: str, b: str, src: ModuleSource, node: ast.AST
    ) -> None:
        key = (a, b)
        if key not in self._edges:
            self._edges[key] = (src.path, getattr(node, "lineno", 1))
            self._edge_order.append(key)

    # -- cross-file cycle check ------------------------------------------------

    def finalize(self) -> List[Finding]:
        """Cycle detection over the aggregated lock graph — runs after
        every scoped file has contributed its edges."""
        adj: Dict[str, List[str]] = {}
        for a, b in self._edge_order:
            adj.setdefault(a, []).append(b)
        out: List[Finding] = []
        seen_cycles: Set[Tuple[str, ...]] = set()
        state: Dict[str, int] = {}  # 0 visiting / 1 done

        def dfs(nod: str, stack: List[str]) -> None:
            state[nod] = 0
            stack.append(nod)
            for nxt in adj.get(nod, ()):
                if state.get(nxt) == 0:
                    cyc = tuple(stack[stack.index(nxt):]) + (nxt,)
                    # Canonical rotation so each cycle reports once.
                    body = cyc[:-1]
                    k = min(range(len(body)), key=lambda i: body[i])
                    canon = body[k:] + body[:k]
                    if canon not in seen_cycles:
                        seen_cycles.add(canon)
                        path, line = self._edges[(nod, nxt)]
                        out.append(
                            Finding(
                                rule=self.id,
                                path=path,
                                line=line,
                                col=1,
                                message=(
                                    "lock-order cycle: "
                                    + " -> ".join(canon + (canon[0],))
                                    + " — two threads taking these in "
                                    "opposite order deadlock; impose "
                                    "one order or split the hold"
                                ),
                            )
                        )
                elif state.get(nxt) is None:
                    dfs(nxt, stack)
            stack.pop()
            state[nod] = 1

        for nod in list(adj):
            if nod not in state:
                dfs(nod, [])
        return out


def _term(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""
