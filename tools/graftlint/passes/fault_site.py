"""fault-site: every production injection site has a documented recovery.

The chaos layer (``fluidframework_tpu/testing/faults.py``) threads named
``@inject_fault("<site>")`` boundaries through production modules. Its
correctness story depends on two invariants this pass enforces
STATICALLY (the runtime also raises on unknown sites, but a site in a
rarely-imported module would only trip at import time — the lint gate
trips at commit time):

- every site name used in a production module is a STRING LITERAL that
  appears in the documented vocabulary (``faults.SITES``), so the
  contract table in ``docs/failure-semantics.md`` can never silently lag
  the code; and
- every vocabulary entry maps to a registered recovery kind
  (``faults.RECOVERY_KINDS``): an injection site whose failure nobody
  catches is a latent outage, not a chaos harness.

Like wire-drift, this pass has no pragma: the acceptance mechanism for a
new site IS declaring it in the vocabulary (one dict entry naming its
recovery), which the docs table and the chaos matrix then cover.

The vocabulary is parsed from the faults module's AST — the pass never
imports package code.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.graftlint import config
from tools.graftlint.core import Finding, ModuleSource, scope_files


def _parse_vocabulary(path: str) -> Tuple[Dict[str, str], Set[str]]:
    """(SITES dict, RECOVERY_KINDS set) from the faults module's source —
    both are pure literals by construction (this parse is why)."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    sites: Dict[str, str] = {}
    kinds: Set[str] = set()
    for node in tree.body:
        targets: List[ast.AST] = []
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        names = {t.id for t in targets if isinstance(t, ast.Name)}
        if "SITES" in names and isinstance(value, ast.Dict):
            for k, v in zip(value.keys, value.values):
                if isinstance(k, ast.Constant) and isinstance(
                    v, ast.Constant
                ):
                    sites[str(k.value)] = str(v.value)
        if "RECOVERY_KINDS" in names:
            for sub in ast.walk(value):
                if isinstance(sub, ast.Constant) and isinstance(
                    sub.value, str
                ):
                    kinds.add(sub.value)
    return sites, kinds


def _is_inject_call(func: ast.AST) -> bool:
    if isinstance(func, ast.Name):
        return func.id == "inject_fault"
    if isinstance(func, ast.Attribute):
        return func.attr == "inject_fault"
    return False


class FaultSitePass:
    id = "fault-site"

    def __init__(self) -> None:
        self._root: Optional[str] = None
        self._vocab: Dict[str, Tuple[Dict[str, str], Set[str]]] = {}

    def scope(self, root: str) -> List[str]:
        self._root = root
        return [
            p
            for p in scope_files(root, config.FAULT_SITE_SCOPE)
            if not p.startswith("fluidframework_tpu/testing/")
        ]

    def _vocabulary(self) -> Tuple[Dict[str, str], Set[str]]:
        root = self._root or config.REPO_ROOT
        if root not in self._vocab:
            path = os.path.join(root, config.FAULT_VOCAB_MODULE)
            if not os.path.exists(path):
                # Fixture roots without a vocabulary module validate
                # against the repo's real one.
                path = os.path.join(
                    config.REPO_ROOT, config.FAULT_VOCAB_MODULE
                )
            self._vocab[root] = _parse_vocabulary(path)
        return self._vocab[root]

    def run(self, src: ModuleSource) -> Iterator[Tuple[Finding, ast.AST]]:
        sites, kinds = self._vocabulary()
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) or not _is_inject_call(
                node.func
            ):
                continue
            if len(node.args) != 1 or not (
                isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                yield (
                    src.finding(
                        self.id,
                        node,
                        "inject_fault site name must be a single string "
                        "literal — the vocabulary and its recovery "
                        "contract are checked statically",
                    ),
                    node,
                )
                continue
            site = node.args[0].value
            if site not in sites:
                yield (
                    src.finding(
                        self.id,
                        node,
                        f"unknown injection site {site!r} — declare it in "
                        "testing/faults.py SITES with its recovery "
                        "contract (docs/failure-semantics.md)",
                    ),
                    node,
                )
            elif sites[site] not in kinds:
                yield (
                    src.finding(
                        self.id,
                        node,
                        f"injection site {site!r} has no registered "
                        f"recovery policy (SITES maps it to "
                        f"{sites[site]!r}, not a documented recovery "
                        "kind)",
                    ),
                    node,
                )
