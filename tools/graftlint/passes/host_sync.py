"""host-sync: implicit device→host transfers in device-path modules.

The serving paths stage uploads and drain readbacks deliberately — every
transfer is part of a documented cost model (the tunnel moves single-digit
MB/s). An ``int(device_scalar)`` that creeps into a loop, or an
``np.asarray(pool.state.err)`` added for a quick stat, is a synchronous
device round-trip the profiles will blame on the kernels. This pass flags
them all; intentional ones carry ``# graftlint: readback(<reason>)``.

Detection is a single-forward-pass local taint analysis, not type
inference: an expression is *device-tainted* when it reaches through

- an attribute whose terminal name is a known device-state idiom
  (``config.DEVICE_ATTRS``: ``pool.state``, ``self.tables``, ...);
- a call to a jit-built function (module-level ``x = jax.jit(...)``,
  ``@jax.jit`` / ``@functools.partial(jax.jit, ...)`` defs);
- a call to anything imported from the kernel modules
  (``config.KERNEL_MODULE_PREFIXES``);
- a call into ``jnp.*`` / ``jax.device_put``;
- a local name last assigned from a tainted expression (loop targets over
  tainted iterables included).

``np.asarray``/``np.array`` over a tainted argument is the readback
boundary: the call is flagged and its RESULT is host (so downstream
``int()`` over it is clean). ``.item()`` and ``block_until_ready`` are
flagged unconditionally — in a device-path module there is no innocent
reading of either.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.graftlint import config
from tools.graftlint.core import Finding, ModuleSource, scope_files

_SCALARIZERS = ("int", "float", "bool")


def _is_np(func: ast.AST, names: Tuple[str, ...]) -> bool:
    """``np.asarray`` / ``numpy.array`` style attribute calls."""
    return (
        isinstance(func, ast.Attribute)
        and func.attr in names
        and isinstance(func.value, ast.Name)
        and func.value.id in ("np", "numpy")
    )


def _is_jnp_call(func: ast.AST) -> bool:
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        if func.value.id == "jnp":
            return True
        if func.value.id == "jax" and func.attr == "device_put":
            return True
    return False


def _decorated_jit(fn: ast.AST) -> bool:
    """``@jax.jit`` or ``@functools.partial(jax.jit, ...)`` (also bare
    ``partial(jax.jit, ...)``)."""
    for dec in getattr(fn, "decorator_list", []):
        if (
            isinstance(dec, ast.Attribute)
            and dec.attr == "jit"
            and isinstance(dec.value, ast.Name)
            and dec.value.id == "jax"
        ):
            return True
        if isinstance(dec, ast.Call):
            f = dec.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "jit"
                and isinstance(f.value, ast.Name)
                and f.value.id == "jax"
            ):
                return True
            is_partial = (
                isinstance(f, ast.Name) and f.id == "partial"
            ) or (
                isinstance(f, ast.Attribute)
                and f.attr == "partial"
                and isinstance(f.value, ast.Name)
                and f.value.id == "functools"
            )
            if is_partial and dec.args:
                a0 = dec.args[0]
                if (
                    isinstance(a0, ast.Attribute)
                    and a0.attr == "jit"
                    and isinstance(a0.value, ast.Name)
                    and a0.value.id == "jax"
                ):
                    return True
    return False


def device_fn_names(tree: ast.AST) -> Set[str]:
    """Module-level names whose CALL yields a device value: jit-built
    callables and kernel-module imports."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            if node.module.startswith(config.KERNEL_MODULE_PREFIXES):
                for alias in node.names:
                    name = alias.asname or alias.name
                    # Functions only: CamelCase imports are container
                    # constructors (SegmentState) whose taint follows
                    # their arguments, ALL_CAPS are constants.
                    if name[:1].islower():
                        out.add(name)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _decorated_jit(node):
                out.add(node.name)
        elif isinstance(node, ast.Assign):
            v = node.value
            if (
                isinstance(v, ast.Call)
                and isinstance(v.func, ast.Attribute)
                and v.func.attr == "jit"
                and isinstance(v.func.value, ast.Name)
                and v.func.value.id == "jax"
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def _seed_params(taint: "_Taint", fn: ast.AST) -> None:
    """Device-param contract (``config.DEVICE_PARAM_FNS``): the off-loop
    transfer halves receive concrete device arrays by design — their
    parameters START tainted so the np.asarray inside is a verified
    readback, not an invisible one."""
    if getattr(fn, "name", None) not in config.DEVICE_PARAM_FNS:
        return
    a = fn.args
    for arg in (
        list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
    ):
        if arg.arg not in ("self", "cls"):
            taint.env[arg.arg] = True


def device_method_names(
    tree: ast.AST, device_fns: Set[str]
) -> Tuple[Set[str], Set[str]]:
    """(device-returning names, ALL local function names): same-module
    functions/methods whose return value is device-tainted, as a fixed
    point (a method returning another device method's result is itself
    a device source). The full name set makes the summaries
    authoritative — a local call NOT in the device set returns host."""
    fns = [
        n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    local = {fn.name for fn in fns}
    methods: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for fn in fns:
            if fn.name in methods:
                continue
            if _returns_tainted(fn, device_fns, methods, local):
                methods.add(fn.name)
                changed = True
    return methods, local


def _returns_tainted(
    fn: ast.AST, device_fns: Set[str], methods: Set[str], local: Set[str]
) -> bool:
    taint = _Taint(device_fns, methods, local)
    _seed_params(taint, fn)
    found = False

    def walk(body) -> None:
        nonlocal found
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if (
                isinstance(stmt, ast.Return)
                and stmt.value is not None
                and taint.tainted(stmt.value)
            ):
                found = True
            if isinstance(stmt, ast.Assign):
                v = taint.tainted(stmt.value)
                for t in stmt.targets:
                    taint.bind(t, v)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                taint.bind(stmt.target, taint.tainted(stmt.value))
            elif isinstance(stmt, ast.AugAssign):
                if taint.tainted(stmt.value):
                    taint.bind(stmt.target, True)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                taint.bind(stmt.target, taint.tainted(stmt.iter))
                walk(stmt.body)
                walk(stmt.orelse)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                walk(stmt.body)
            elif isinstance(stmt, (ast.If, ast.While)):
                walk(stmt.body)
                walk(stmt.orelse)
            elif isinstance(stmt, ast.Try):
                for blk in (stmt.body, stmt.orelse, stmt.finalbody):
                    walk(blk)
                for h in stmt.handlers:
                    walk(h.body)

    walk(fn.body)
    return found


class _Taint:
    """Local device-taint evaluation for one function (or module) body.

    ``device_methods`` are same-module functions/methods whose RETURN is
    device-tainted (computed by :func:`device_method_names` as a fixed
    point) — ``self._telemetry_device()`` is as much a device source as
    a jitted call, and without the summary the readback pragma on its
    consumer would be unverifiable."""

    def __init__(
        self,
        device_fns: Set[str],
        device_methods: Set[str] = frozenset(),
        local_fns: Set[str] = frozenset(),
    ):
        self.device_fns = device_fns
        self.device_methods = device_methods
        # Every same-module function name: where a summary exists it is
        # AUTHORITATIVE — a local call not in device_methods returns
        # host, even over tainted args (the generic carries-taint rule
        # is for constructors/unknown callees only).
        self.local_fns = local_fns
        self.env: Dict[str, bool] = {}

    # -- expression taint ------------------------------------------------------

    def tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return self.env.get(node.id, False)
        if isinstance(node, ast.Attribute):
            if node.attr in ("shape", "ndim", "dtype", "size"):
                return False  # array metadata lives on host
            if node.attr in config.DEVICE_ATTRS:
                return True
            return self.tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self.tainted(node.value)
        if isinstance(node, ast.Call):
            f = node.func
            # The readback boundary: the result of np.asarray/np.array is
            # HOST regardless of the argument.
            if _is_np(f, ("asarray", "array")):
                return False
            if isinstance(f, ast.Name):
                if f.id in self.device_fns:
                    return True
                if f.id == "getattr" and node.args:
                    return self.tainted(node.args[0])
                if f.id in _SCALARIZERS + ("len", "str", "repr", "range"):
                    return False
            if _is_jnp_call(f):
                return True
            # Same-module functions/methods: the computed return-taint
            # summary decides, in either direction.
            if isinstance(f, ast.Name) and f.id in self.local_fns:
                return f.id in self.device_methods
            if (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id in ("self", "cls")
                and f.attr in self.local_fns
            ):
                return f.attr in self.device_methods
            # Method call on a tainted receiver stays on device
            # (dev.sum(), state._replace(...), tainted[i].max()).
            if isinstance(f, ast.Attribute):
                if f.attr in ("tolist", "item"):
                    return False  # readback boundary (flagged separately)
                if self.tainted(f.value):
                    return True
            # A constructor over tainted elements carries the taint
            # (SegmentState(*[...]) of device lanes is still device).
            return any(
                self.tainted(a)
                for a in list(node.args)
                + [kw.value for kw in node.keywords]
            )
        if isinstance(node, ast.Starred):
            return self.tainted(node.value)
        if isinstance(node, (ast.BinOp,)):
            return self.tainted(node.left) or self.tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.tainted(node.operand)
        if isinstance(node, ast.IfExp):
            return self.tainted(node.body) or self.tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.tainted(e) for e in node.elts)
        if isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            sub = self._comp_scope(node.generators)
            return sub._eval_in(node.elt)
        return False

    def _comp_scope(self, generators) -> "_Taint":
        sub = _Taint(self.device_fns, self.device_methods, self.local_fns)
        sub.env = dict(self.env)
        for gen in generators:
            if sub.tainted(gen.iter):
                sub.bind(gen.target, True)
        return sub

    def _eval_in(self, node: ast.AST) -> bool:
        return self.tainted(node)

    # -- binding ---------------------------------------------------------------

    def bind(self, target: ast.AST, value: bool) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = value
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self.bind(e, value)
        elif isinstance(target, ast.Starred):
            self.bind(target.value, value)
        # attribute/subscript targets: taint follows DEVICE_ATTRS, not env


class HostSyncPass:
    id = "host-sync"

    def scope(self, root: str) -> List[str]:
        return scope_files(root, config.DEVICE_PATH_SCOPE)

    def run(self, src: ModuleSource) -> Iterator[Tuple[Finding, ast.AST]]:
        device_fns = device_fn_names(src.tree)
        device_methods, local_fns = device_method_names(
            src.tree, device_fns
        )
        # Module body + every function body, each with a fresh local env.
        yield from self._walk_body(
            src,
            src.tree.body,
            _Taint(device_fns, device_methods, local_fns),
            (device_fns, device_methods, local_fns),
        )

    # -- statement walk --------------------------------------------------------

    def _walk_body(
        self,
        src: ModuleSource,
        body: List[ast.stmt],
        taint: _Taint,
        device_fns,
    ) -> Iterator[Tuple[Finding, ast.AST]]:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Fresh local scope; parameters start untainted (callers
                # own their transfers) — EXCEPT the declared off-loop
                # transfer halves, whose params are device by contract.
                sub = _Taint(*device_fns)
                _seed_params(sub, stmt)
                yield from self._walk_body(src, stmt.body, sub, device_fns)
                continue
            if isinstance(stmt, ast.ClassDef):
                yield from self._walk_body(src, stmt.body, taint, device_fns)
                continue
            # Flag readbacks in this statement's own expressions (compound
            # statements contribute only their headers here — their bodies
            # re-enter _walk_body below so the env stays in order).
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                roots: List[ast.AST] = [stmt.iter]
            elif isinstance(stmt, (ast.If, ast.While)):
                roots = [stmt.test]
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                roots = [i.context_expr for i in stmt.items]
            elif isinstance(stmt, ast.Try):
                roots = []
            else:
                roots = [stmt]
            for root in roots:
                yield from self._check_expr(src, root, stmt, taint)
            # Update bindings AFTER flagging (the RHS is evaluated with
            # the pre-assignment env).
            if isinstance(stmt, ast.Assign):
                v = taint.tainted(stmt.value)
                for t in stmt.targets:
                    taint.bind(t, v)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                taint.bind(stmt.target, taint.tainted(stmt.value))
            elif isinstance(stmt, ast.AugAssign):
                if taint.tainted(stmt.value):
                    taint.bind(stmt.target, True)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                taint.bind(stmt.target, taint.tainted(stmt.iter))
                yield from self._walk_body(src, stmt.body, taint, device_fns)
                yield from self._walk_body(
                    src, stmt.orelse, taint, device_fns
                )
                continue
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from self._walk_body(src, stmt.body, taint, device_fns)
                continue
            elif isinstance(stmt, (ast.If, ast.While)):
                yield from self._walk_body(src, stmt.body, taint, device_fns)
                yield from self._walk_body(
                    src, stmt.orelse, taint, device_fns
                )
                continue
            elif isinstance(stmt, ast.Try):
                for blk in (stmt.body, stmt.orelse, stmt.finalbody):
                    yield from self._walk_body(src, blk, taint, device_fns)
                for h in stmt.handlers:
                    yield from self._walk_body(src, h.body, taint, device_fns)
                continue

    def _check_expr(
        self, src: ModuleSource, root: ast.AST, stmt: ast.stmt, taint: _Taint
    ) -> Iterator[Tuple[Finding, ast.AST]]:
        """Flag readbacks anywhere under one expression root, evaluating
        taint in the statement's current env (with comprehension-local
        bindings rebuilt for nodes inside comprehensions)."""
        for node in ast.walk(root):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs walk separately
            if not isinstance(node, (ast.Call, ast.Attribute)):
                continue
            env = taint
            # Rebuild comprehension-local taint for nodes inside
            # comprehensions (ast.walk loses that context, so find the
            # nearest comprehension ancestor by identity containment).
            comp = _enclosing_comp(root, node)
            if comp is not None:
                env = taint._comp_scope(comp.generators)
            if isinstance(node, ast.Attribute):
                if node.attr == "block_until_ready":
                    yield (
                        src.finding(
                            self.id,
                            node,
                            "block_until_ready is a host sync barrier — "
                            "annotate `# graftlint: readback(<reason>)` "
                            "if this device-path sync is intentional",
                        ),
                        stmt,
                    )
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "item":
                yield (
                    src.finding(
                        self.id,
                        node,
                        ".item() reads one scalar back per call — "
                        "batch the readback or annotate "
                        "`# graftlint: readback(<reason>)`",
                    ),
                    stmt,
                )
                continue
            if isinstance(f, ast.Attribute) and f.attr == "tolist":
                if env.tainted(f.value):
                    yield (
                        src.finding(
                            self.id,
                            node,
                            ".tolist() on a device value is an implicit "
                            "device→host transfer — annotate "
                            "`# graftlint: readback(<reason>)` or go "
                            "through one staged np.asarray",
                        ),
                        stmt,
                    )
                continue
            if _is_np(f, ("asarray", "array")):
                if node.args and env.tainted(node.args[0]):
                    name = ast.unparse(node.args[0])
                    yield (
                        src.finding(
                            self.id,
                            node,
                            f"np.{f.attr}({name}) is an implicit "
                            "device→host transfer — annotate "
                            "`# graftlint: readback(<reason>)` or keep "
                            "the value on device",
                        ),
                        stmt,
                    )
                continue
            if (
                isinstance(f, ast.Name)
                and f.id in _SCALARIZERS
                and len(node.args) == 1
                and env.tainted(node.args[0])
            ):
                name = ast.unparse(node.args[0])
                yield (
                    src.finding(
                        self.id,
                        node,
                        f"{f.id}({name}) scalarizes a device value "
                        "(one blocking transfer per call) — annotate "
                        "`# graftlint: readback(<reason>)` or batch via "
                        "one np.asarray",
                    ),
                    stmt,
                )


def _enclosing_comp(
    root: ast.AST, node: ast.AST
) -> Optional[ast.expr]:
    """Nearest comprehension in ``root`` that strictly contains ``node``
    (by identity walk)."""
    best = None
    for cand in ast.walk(root):
        if isinstance(
            cand, (ast.ListComp, ast.GeneratorExp, ast.SetComp, ast.DictComp)
        ):
            for sub in ast.walk(cand):
                if sub is node and cand is not node:
                    best = cand
                    break
    return best
