"""vocab-drift: the observability vocabularies cross-checked statically.

r9–r16 grew five hand-maintained vocabularies that runtime code raises
on — ``faults.SITES`` (injection sites), ``journal.EVENTS`` (flight-
recorder kinds), ``profiler.LANES`` (timeline lanes), the trace-spine
stage constants (``tracing.FRAME_STAGES``), and — declared in r17 —
``metrics.FAMILIES`` (Prometheus families). The runtime check only
trips when the producing line EXECUTES; a drifted string in a rarely-hit
branch (a typo'd journal kind in an error path, a stage stamped under a
name the span reducer ignores) ships silently. This pass is the
wire-fingerprint idea applied to the observability vocabularies: every
string used as a site/kind/lane/stage/family in the package must appear
in its declared vocabulary, AND every declared entry must be used —
drift fails lint in either direction:

- ``journal.record("<kind>", …)`` / ``JOURNAL.record`` — kind must be a
  string literal in ``journal.EVENTS``;
- ``profiler.record("<lane>", …)`` / ``PROFILER.record`` — lane must be
  a string literal in ``profiler.LANES`` (``config.DERIVED_LANES`` are
  synthesized by read surfaces and exempt from the dead-entry check);
- ``tracing.stamp(traces, <stage>, …)`` — a literal stage must be in
  the ``FRAME_STAGES`` vocabulary; a ``STAGE_*`` constant must resolve
  to one;
- ``reg.counter/gauge/histogram("<family>", …)`` — family must be
  declared in ``metrics.FAMILIES`` with a MATCHING kind;
- every ``faults.SITES`` site must decorate at least one production
  boundary (unknown/non-literal sites are the fault-site pass's job;
  this pass owns the DEAD direction).

Like wire-drift and fault-site, there is no pragma: the acceptance
mechanism for a new name IS declaring it in its vocabulary (and for a
dead one, deleting it). Vocabularies are parsed from module source —
the pass never imports package code.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.graftlint import config
from tools.graftlint.core import Finding, ModuleSource, scope_files
from tools.graftlint.passes.fault_site import _parse_vocabulary


def _parse_dict_vocab(
    path: str, var_name: str
) -> Tuple[Dict[str, int], str]:
    """String keys (with their source lines) of a module-level dict
    literal assignment ``VAR: … = {…}``. Returns ({key: lineno},
    relpath-ish label)."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    out: Dict[str, int] = {}
    for node in tree.body:
        targets: List[ast.AST] = []
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        names = {t.id for t in targets if isinstance(t, ast.Name)}
        if var_name in names and isinstance(value, ast.Dict):
            for k in value.keys:
                if isinstance(k, ast.Constant) and isinstance(
                    k.value, str
                ):
                    out[k.value] = k.lineno
    return out, var_name


def _parse_stage_vocab(path: str) -> Tuple[Dict[str, str], Dict[str, int]]:
    """(STAGE_* constant name -> stage string, stage string -> lineno
    for FRAME_STAGES members)."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    consts: Dict[str, str] = {}
    const_lines: Dict[str, int] = {}
    frame_stage_names: List[str] = []
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if not isinstance(t, ast.Name):
                continue
            if t.id.startswith("STAGE_") and isinstance(
                node.value, ast.Constant
            ):
                consts[t.id] = str(node.value.value)
                const_lines[t.id] = node.lineno
            elif t.id == "FRAME_STAGES" and isinstance(
                node.value, (ast.Tuple, ast.List)
            ):
                for e in node.value.elts:
                    if isinstance(e, ast.Name):
                        frame_stage_names.append(e.id)
                    elif isinstance(e, ast.Constant):
                        frame_stage_names.append(str(e.value))
    stages: Dict[str, int] = {}
    for name in frame_stage_names:
        if name in consts:
            stages[consts[name]] = const_lines[name]
        else:
            stages[name] = 1
    return consts, stages


def _parse_families(path: str) -> Tuple[Dict[str, str], Dict[str, int]]:
    """(family -> kind, family -> lineno) from metrics.FAMILIES."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    kinds: Dict[str, str] = {}
    lines: Dict[str, int] = {}
    for node in tree.body:
        targets: List[ast.AST] = []
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        names = {t.id for t in targets if isinstance(t, ast.Name)}
        if "FAMILIES" in names and isinstance(value, ast.Dict):
            for k, v in zip(value.keys, value.values):
                if isinstance(k, ast.Constant) and isinstance(
                    v, ast.Constant
                ):
                    kinds[str(k.value)] = str(v.value)
                    lines[str(k.value)] = k.lineno
    return kinds, lines


def _term(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


class _Vocab:
    """One root's parsed vocabularies + cross-file usage accumulators."""

    def __init__(self, root: str) -> None:
        def resolve(rel: str) -> str:
            path = os.path.join(root, rel)
            if not os.path.exists(path):
                # Fixture roots validate against the repo's real
                # vocabularies (the fault-site pass convention).
                path = os.path.join(config.REPO_ROOT, rel)
            return path

        self.sites, _ = _parse_vocabulary(resolve(config.FAULT_VOCAB_MODULE))
        self.events, _ = _parse_dict_vocab(
            resolve(config.JOURNAL_VOCAB_MODULE), "EVENTS"
        )
        self.lanes, _ = _parse_dict_vocab(
            resolve(config.PROFILER_VOCAB_MODULE), "LANES"
        )
        self.stage_consts, self.stages = _parse_stage_vocab(
            resolve(config.TRACING_VOCAB_MODULE)
        )
        self.families, self.family_lines = _parse_families(
            resolve(config.METRICS_VOCAB_MODULE)
        )
        self.used_sites: Set[str] = set()
        self.used_events: Set[str] = set()
        self.used_lanes: Set[str] = set()
        self.used_stages: Set[str] = set()
        self.used_families: Set[str] = set()


class VocabDriftPass:
    id = "vocab-drift"

    def __init__(self) -> None:
        self._root: Optional[str] = None
        self._vocab: Dict[str, _Vocab] = {}

    def scope(self, root: str) -> List[str]:
        self._root = root
        self._vocab.pop(root, None)  # fresh usage accumulators per run
        return scope_files(root, config.VOCAB_SCOPE)

    def vocabulary(self) -> _Vocab:
        root = self._root or config.REPO_ROOT
        if root not in self._vocab:
            self._vocab[root] = _Vocab(root)
        return self._vocab[root]

    # -- usage detection -------------------------------------------------------

    def run(self, src: ModuleSource) -> Iterator[Tuple[Finding, ast.AST]]:
        v = self.vocabulary()
        is_journal_mod = src.path == config.JOURNAL_VOCAB_MODULE
        is_profiler_mod = src.path == config.PROFILER_VOCAB_MODULE
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            fname = _term(f)
            recv = _term(f.value) if isinstance(f, ast.Attribute) else ""
            # inject_fault sites: usage only (fault-site flags unknowns).
            if fname == "inject_fault":
                if (
                    len(node.args) == 1
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    v.used_sites.add(node.args[0].value)
                continue
            # journal.record("<kind>", …) / profiler.record("<lane>", …)
            if fname == "record":
                table = None
                used = None
                what = where = ""
                if recv in ("journal", "JOURNAL") or (
                    isinstance(f, ast.Name) and is_journal_mod
                ) or (recv == "self" and is_journal_mod):
                    table, used = v.events, v.used_events
                    what, where = "journal event kind", "telemetry/journal.py EVENTS"
                elif recv in ("profiler", "PROFILER") or (
                    isinstance(f, ast.Name) and is_profiler_mod
                ) or (recv == "self" and is_profiler_mod):
                    table, used = v.lanes, v.used_lanes
                    what, where = "profiler lane", "telemetry/profiler.py LANES"
                if table is None or not node.args:
                    continue
                a0 = node.args[0]
                # A two-literal conditional kind is static enough
                # (`"admission.admit" if d.admitted else
                # "admission.deny"`): both arms check and count.
                if (
                    isinstance(a0, ast.IfExp)
                    and isinstance(a0.body, ast.Constant)
                    and isinstance(a0.body.value, str)
                    and isinstance(a0.orelse, ast.Constant)
                    and isinstance(a0.orelse.value, str)
                ):
                    for arm in (a0.body, a0.orelse):
                        used.add(arm.value)
                        if arm.value not in table:
                            yield (
                                src.finding(
                                    self.id,
                                    node,
                                    f"undeclared {what} "
                                    f"{arm.value!r} — declare it in "
                                    f"{where}",
                                ),
                                node,
                            )
                    continue
                if not (
                    isinstance(a0, ast.Constant)
                    and isinstance(a0.value, str)
                ):
                    # The vocabulary module's own delegating shim
                    # (record(lane, …) forwarding to the ring) is the
                    # one sanctioned non-literal producer.
                    if not (is_journal_mod or is_profiler_mod):
                        yield (
                            src.finding(
                                self.id,
                                node,
                                f"{what} must be a single string literal "
                                "— the vocabulary is checked statically",
                            ),
                            node,
                        )
                    continue
                used.add(a0.value)
                if a0.value not in table:
                    yield (
                        src.finding(
                            self.id,
                            node,
                            f"undeclared {what} {a0.value!r} — declare "
                            f"it in {where} (unknown names raise at "
                            "runtime, but only when the branch runs)",
                        ),
                        node,
                    )
                continue
            # tracing.stamp(traces, <stage>, …)
            if fname == "stamp" and len(node.args) >= 2:
                a1 = node.args[1]
                if isinstance(a1, ast.Constant) and isinstance(
                    a1.value, str
                ):
                    v.used_stages.add(a1.value)
                    if a1.value not in v.stages:
                        yield (
                            src.finding(
                                self.id,
                                node,
                                f"stage {a1.value!r} is not in the "
                                "trace-spine vocabulary "
                                "(tracing.FRAME_STAGES) — the span "
                                "reducer drops unknown stages silently",
                            ),
                            node,
                        )
                else:
                    cname = _term(a1)
                    if cname.startswith("STAGE_"):
                        stage = v.stage_consts.get(cname)
                        if stage is None:
                            yield (
                                src.finding(
                                    self.id,
                                    node,
                                    f"unknown trace-stage constant "
                                    f"{cname} — tracing.py declares the "
                                    "stage vocabulary",
                                ),
                                node,
                            )
                        else:
                            v.used_stages.add(stage)
                continue
            # Registry family registrations.
            if fname in ("counter", "gauge", "histogram") and recv.lower() in (
                "reg",
                "registry",
            ):
                if not node.args or not (
                    isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    yield (
                        src.finding(
                            self.id,
                            node,
                            "metric family name must be a string "
                            "literal — metrics.FAMILIES is the "
                            "exposition contract, checked statically",
                        ),
                        node,
                    )
                    continue
                fam = node.args[0].value
                v.used_families.add(fam)
                if fam not in v.families:
                    yield (
                        src.finding(
                            self.id,
                            node,
                            f"undeclared Prometheus family {fam!r} — "
                            "declare it in telemetry/metrics.py "
                            "FAMILIES with its kind",
                        ),
                        node,
                    )
                elif v.families[fam] != fname:
                    yield (
                        src.finding(
                            self.id,
                            node,
                            f"family {fam!r} registered as {fname} but "
                            f"declared {v.families[fam]!r} in "
                            "metrics.FAMILIES — one family, one kind",
                        ),
                        node,
                    )

    # -- dead-entry direction --------------------------------------------------

    def finalize(self) -> List[Finding]:
        """Declared-but-unused vocabulary entries, reported at their
        declaration lines — only meaningful after the WHOLE scope has
        been scanned (the runner skips finalize under a paths filter)."""
        v = self.vocabulary()
        out: List[Finding] = []

        def dead(
            entries, used: Set[str], path: str, what: str, line_of
        ) -> None:
            for name in sorted(entries):
                if name in used:
                    continue
                out.append(
                    Finding(
                        rule=self.id,
                        path=path,
                        line=line_of(name),
                        col=1,
                        message=(
                            f"dead {what} {name!r}: declared but never "
                            "used by any production module — delete it "
                            "or wire the producer (dead vocabulary "
                            "rows misdocument the observability "
                            "surface)"
                        ),
                    )
                )

        dead(
            v.sites,
            v.used_sites,
            config.FAULT_VOCAB_MODULE,
            "fault site",
            lambda n: 1,
        )
        dead(
            v.events,
            v.used_events,
            config.JOURNAL_VOCAB_MODULE,
            "journal event kind",
            lambda n: v.events[n],
        )
        dead(
            {
                lane: ln
                for lane, ln in v.lanes.items()
                if lane not in config.DERIVED_LANES
            },
            v.used_lanes,
            config.PROFILER_VOCAB_MODULE,
            "profiler lane",
            lambda n: v.lanes[n],
        )
        dead(
            v.stages,
            v.used_stages,
            config.TRACING_VOCAB_MODULE,
            "trace-spine stage",
            lambda n: v.stages[n],
        )
        dead(
            v.families,
            v.used_families,
            config.METRICS_VOCAB_MODULE,
            "Prometheus family",
            lambda n: v.family_lines[n],
        )
        return out
