"""recompile-hazard: jit/kernel construction that defeats the compile
cache, and traced-value Python branches inside jitted functions.

The serving paths stay fast because compilation happens once per shape:
jitted steps live at module level (``_jit_step = jax.jit(...)``) or
behind ``functools.lru_cache`` builders (``_mesh_pallas_step``). A
``jax.jit``/``pallas_call`` constructed inside a loop — or inside a plain
per-call function — builds a fresh callable each time, and on the
tunneled TPU backend one stray recompile is a multi-second stall in the
middle of a flush.

Rules:

- ``jax.jit(...)`` / ``functools.partial(jax.jit, ...)`` / ``pl.pallas_call``
  / ``.lower(...).compile()`` inside a ``for``/``while`` body: flagged.
- The same constructions inside a function body (not module level):
  flagged unless the enclosing function is cached
  (``functools.lru_cache``/``cache``) or is itself jit-decorated
  (``pallas_call`` under a jitted entry point traces once per shape
  through the jit cache).
- Inside a jit-decorated function, ``if``/``while`` tests that reference
  a NON-static parameter directly (not through ``.shape``/``.ndim``/
  ``.dtype``, which are static at trace time): flagged as
  shape-dependent Python branching on a traced value.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from tools.graftlint import config
from tools.graftlint.core import Finding, ModuleSource, scope_files
from tools.graftlint.passes.host_sync import _decorated_jit

_STATIC_ATTRS = ("shape", "ndim", "dtype", "size")


def _is_jit_ctor(node: ast.Call) -> Optional[str]:
    """'jax.jit' / 'pallas_call' / 'compile' when this call constructs a
    compiled callable."""
    f = node.func
    if (
        isinstance(f, ast.Attribute)
        and f.attr == "jit"
        and isinstance(f.value, ast.Name)
        and f.value.id == "jax"
    ):
        return "jax.jit"
    if isinstance(f, ast.Attribute) and f.attr == "pallas_call":
        return "pallas_call"
    if isinstance(f, ast.Name) and f.id == "pallas_call":
        return "pallas_call"
    # functools.partial(jax.jit, ...) used as a value
    is_partial = (
        isinstance(f, ast.Name) and f.id == "partial"
    ) or (
        isinstance(f, ast.Attribute)
        and f.attr == "partial"
        and isinstance(f.value, ast.Name)
        and f.value.id == "functools"
    )
    if is_partial and node.args:
        a0 = node.args[0]
        if (
            isinstance(a0, ast.Attribute)
            and a0.attr == "jit"
            and isinstance(a0.value, ast.Name)
            and a0.value.id == "jax"
        ):
            return "functools.partial(jax.jit, ...)"
    # X.lower(...).compile()
    if (
        isinstance(f, ast.Attribute)
        and f.attr == "compile"
        and isinstance(f.value, ast.Call)
        and isinstance(f.value.func, ast.Attribute)
        and f.value.func.attr == "lower"
    ):
        return ".lower().compile()"
    return None


def _cached_def(fn: ast.AST) -> bool:
    """Decorated with functools.lru_cache / functools.cache (bare names
    included)."""
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = None
        if isinstance(target, ast.Attribute):
            name = target.attr
        elif isinstance(target, ast.Name):
            name = target.id
        if name in ("lru_cache", "cache"):
            return True
    return False


def _static_params(fn: ast.AST) -> Tuple[Set[str], bool]:
    """(static parameter names, is_jitted) from @jax.jit /
    @functools.partial(jax.jit, static_argnums=..., static_argnames=...)."""
    if not _decorated_jit(fn):
        return set(), False
    args = fn.args
    ordered = [a.arg for a in args.posonlyargs + args.args]
    static: Set[str] = set()
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        for kw in dec.keywords:
            if kw.arg == "static_argnums":
                try:
                    nums = ast.literal_eval(kw.value)
                except ValueError:
                    continue
                if isinstance(nums, int):
                    nums = (nums,)
                for i in nums:
                    if 0 <= i < len(ordered):
                        static.add(ordered[i])
            elif kw.arg == "static_argnames":
                try:
                    names = ast.literal_eval(kw.value)
                except ValueError:
                    continue
                if isinstance(names, str):
                    names = (names,)
                static.update(names)
    # Keyword-only params without static_argnames are still traced, but
    # jit entry points here pass them statically (block_docs=, interpret=)
    # — jax itself errors otherwise, so treat kwonly as static.
    static.update(a.arg for a in args.kwonlyargs)
    return static, True


class RecompileHazardPass:
    id = "recompile-hazard"

    def scope(self, root: str) -> List[str]:
        return scope_files(root, config.DEVICE_PATH_SCOPE)

    def run(self, src: ModuleSource) -> Iterator[Tuple[Finding, ast.AST]]:
        yield from self._walk(src, src.tree.body, fn_stack=[], loop_depth=0)

    def _walk(
        self,
        src: ModuleSource,
        body: List[ast.stmt],
        fn_stack: List[ast.AST],
        loop_depth: int,
    ) -> Iterator[Tuple[Finding, ast.AST]]:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_jit_branches(src, stmt)
                yield from self._walk(
                    src, stmt.body, fn_stack + [stmt], loop_depth=0
                )
                continue
            if isinstance(stmt, ast.ClassDef):
                yield from self._walk(src, stmt.body, fn_stack, loop_depth)
                continue
            in_loop = loop_depth > 0
            # Compound statements contribute only their header
            # expressions here; their bodies recurse below (walking the
            # whole subtree would double-count).
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                roots: List[ast.AST] = [stmt.iter]
            elif isinstance(stmt, (ast.If, ast.While)):
                roots = [stmt.test]
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                roots = [i.context_expr for i in stmt.items]
            elif isinstance(stmt, ast.Try):
                roots = []
            else:
                roots = [stmt]
            for node in (n for r in roots for n in ast.walk(r)):
                if not isinstance(node, ast.Call):
                    continue
                kind = _is_jit_ctor(node)
                if kind is None:
                    continue
                if in_loop:
                    yield (
                        src.finding(
                            self.id,
                            node,
                            f"{kind} constructed inside a loop builds a "
                            "fresh compiled callable per iteration — "
                            "hoist to module level or an lru_cache "
                            "builder",
                        ),
                        stmt,
                    )
                elif fn_stack and not any(
                    _cached_def(f) or _decorated_jit(f) for f in fn_stack
                ):
                    yield (
                        src.finding(
                            self.id,
                            node,
                            f"{kind} constructed per call (enclosing "
                            f"function {fn_stack[-1].name!r} is neither "
                            "cached nor jitted) — each call re-traces; "
                            "hoist to module level or wrap the builder "
                            "in functools.lru_cache",
                        ),
                        stmt,
                    )
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                yield from self._walk(
                    src, stmt.body, fn_stack, loop_depth + 1
                )
                yield from self._walk(
                    src, stmt.orelse, fn_stack, loop_depth
                )
            elif isinstance(stmt, ast.If):
                yield from self._walk(src, stmt.body, fn_stack, loop_depth)
                yield from self._walk(src, stmt.orelse, fn_stack, loop_depth)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from self._walk(src, stmt.body, fn_stack, loop_depth)
            elif isinstance(stmt, ast.Try):
                for blk in (stmt.body, stmt.orelse, stmt.finalbody):
                    yield from self._walk(src, blk, fn_stack, loop_depth)
                for h in stmt.handlers:
                    yield from self._walk(src, h.body, fn_stack, loop_depth)

    def _check_jit_branches(
        self, src: ModuleSource, fn: ast.AST
    ) -> Iterator[Tuple[Finding, ast.AST]]:
        static, jitted = _static_params(fn)
        if not jitted:
            return
        args = fn.args
        traced = {
            a.arg
            for a in args.posonlyargs + args.args
            if a.arg not in static
        }
        if not traced:
            return
        for stmt in ast.walk(fn):
            if not isinstance(stmt, (ast.If, ast.While)):
                continue
            hits = sorted(_traced_refs(stmt.test, traced))
            if hits:
                yield (
                    src.finding(
                        self.id,
                        stmt.test,
                        "Python branch on traced value(s) "
                        f"{', '.join(hits)} inside jitted "
                        f"{fn.name!r} — this is a shape/trace-time "
                        "decision at best and a TracerBoolConversionError "
                        "at worst; use lax.cond/jnp.where or mark the "
                        "argument static",
                    ),
                    stmt,
                )


def _traced_refs(test: ast.AST, traced: Set[str]) -> Set[str]:
    """Traced parameter names the test reads OUTSIDE static attribute
    contexts (.shape/.ndim/.dtype/.size are trace-time constants)."""
    hits: Set[str] = set()

    def visit(node: ast.AST) -> None:
        if (
            isinstance(node, ast.Attribute)
            and node.attr in _STATIC_ATTRS
        ):
            return  # x.shape[...] is static — don't descend into x
        if isinstance(node, ast.Name) and node.id in traced:
            hits.add(node.id)
            return
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(test)
    return hits
