"""loop-blocking: blocking calls reachable from the asyncio serving loop.

The serving tier's worst regressions are no longer wire or kernel bugs —
they are blocking calls that land on the socket event loop: a synchronous
device readback added for a quick stat stalls every connected client for
a device RTT (the hazard that forced the r12 ``scan_transfer``/
``scan_prefetched`` split and the r15 ``read_transfer`` split), a
``time.sleep`` in a ticker freezes delivery, an unbounded lock acquire
deadlocks the loop against a producer thread. The r16 loop-stall
watchdog catches these DYNAMICALLY (``event_loop_lag_ms`` +
``loop.stall``); this pass is the static half — the regression never
ships instead of paging someone.

Analysis (per module, single forward pass):

- **On-loop roots**: every ``async def`` (coroutines run on the loop),
  functions scheduled onto the loop (``loop.call_soon``/``call_later``/
  ``call_soon_threadsafe``/``add_reader``/``add_writer`` arguments), and
  the configured cross-module entry points (``config.LOOP_ENTRY`` — the
  pipeline pump sweep, the device backend's feed/flush/read surface, and
  the lambda handlers all run inside network_server's loop).
- **Local call graph**: a call to a same-module function propagates
  on-loop reachability (bare names and method calls by name). Calls
  appearing as ``run_in_executor``/``Thread(target=…)``/
  ``executor.submit`` arguments are SINKS: the callee runs off-loop.
- **Blocking catalog** inside reachable functions: device→host
  transfers over device-tainted values (``np.asarray``/``np.array``/
  ``.tolist()``/``int()``/``float()``/``bool()`` — the host-sync taint
  machinery, same ``DEVICE_ATTRS``/jit/kernel-import entry rules),
  ``.item()``/``block_until_ready``/``jax.device_get`` always,
  ``time.sleep``, sync file IO (``open``, ``Path.read_text`` family),
  ``subprocess`` calls, sync socket ops, and unbounded
  ``Lock.acquire()``. A DIRECT call to a declared off-loop helper
  (``config.OFF_LOOP_HELPERS``) is also flagged — the split exists so
  the blocking half only ever runs via ``run_in_executor``.

Audited exceptions carry ``# graftlint: onloop(<reason>)`` — e.g. the
quiescence-path scan barrier, which runs on the loop by DESIGN only once
ingest has gone quiet.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tools.graftlint import config
from tools.graftlint.core import Finding, ModuleSource, scope_files
from tools.graftlint.passes.host_sync import (
    _is_np,
    _seed_params,
    _Taint,
    device_fn_names,
    device_method_names,
)

#: Call shapes that move their callable argument OFF the loop: the
#: callee must not be treated as on-loop reachable.
_SINK_ATTRS = frozenset({"run_in_executor"})
_SINK_NAMES = frozenset({"Thread", "Timer"})

#: Call shapes that schedule their callable argument ONTO the loop.
_SCHEDULE_ATTRS = frozenset(
    {
        "call_soon",
        "call_later",
        "call_at",
        "call_soon_threadsafe",
        "add_reader",
        "add_writer",
        "add_done_callback",
    }
)

_SYNC_FILE_ATTRS = frozenset(
    {"read_text", "write_text", "read_bytes", "write_bytes"}
)
_SYNC_SOCKET_ATTRS = frozenset(
    {"recv", "recv_into", "accept", "send", "sendall", "connect",
     "makefile"}
)
_SUBPROCESS_ATTRS = frozenset(
    {"run", "call", "check_call", "check_output", "Popen"}
)


def _terminal_name(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _is_lockish(node: ast.AST) -> bool:
    name = _terminal_name(node)
    return name in config.LOCK_NAMES or name.endswith("_lock")


class _FnInfo:
    __slots__ = ("name", "node", "is_async", "calls", "scheduled")

    def __init__(self, name: str, node: ast.AST, is_async: bool):
        self.name = name
        self.node = node
        self.is_async = is_async
        self.calls: List[Tuple[str, ast.AST]] = []  # callee name, call node


class LoopBlockingPass:
    id = "loop-blocking"

    def scope(self, root: str) -> List[str]:
        return scope_files(root, config.LOOP_SCOPE)

    # -- module structure ------------------------------------------------------

    def _collect_fns(self, tree: ast.AST) -> Dict[str, _FnInfo]:
        """Every function/method in the module, keyed by bare name (a
        name collision unions the call edges — conservative: both
        versions inherit reachability)."""
        fns: Dict[str, _FnInfo] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = _FnInfo(
                    node.name, node, isinstance(node, ast.AsyncFunctionDef)
                )
                prev = fns.get(node.name)
                if prev is not None:
                    info.is_async = info.is_async or prev.is_async
                    info.calls = prev.calls
                fns[node.name] = info
        return fns

    def _own_statements(self, fn: ast.AST) -> Iterator[ast.AST]:
        """Walk a function's body EXCLUDING nested function/lambda
        bodies (those are separate call-graph entries)."""
        stack: List[ast.AST] = list(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _is_sink_call(self, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in _SINK_ATTRS:
            return True
        if isinstance(f, ast.Name) and f.id in _SINK_NAMES:
            return True
        if (
            isinstance(f, ast.Attribute)
            and f.attr in _SINK_NAMES
            and _terminal_name(f.value) == "threading"
        ):
            return True
        return False

    def _edges_and_roots(
        self, fns: Dict[str, _FnInfo]
    ) -> Tuple[Dict[str, _FnInfo], Set[str]]:
        """Populate per-function call edges; return loop-scheduled
        roots. Calls nested inside a sink call's arguments make no
        edge — the callable runs off-loop."""
        scheduled: Set[str] = set()
        for info in fns.values():
            sink_spans: List[Tuple[int, int, int, int]] = []
            for node in self._own_statements(info.node):
                if not isinstance(node, ast.Call):
                    continue
                if self._is_sink_call(node):
                    sink_spans.append(
                        (
                            node.lineno,
                            node.col_offset,
                            node.end_lineno or node.lineno,
                            node.end_col_offset or 0,
                        )
                    )
                    continue
                f = node.func
                if isinstance(f, ast.Attribute) and (
                    f.attr in _SCHEDULE_ATTRS
                ):
                    for arg in node.args:
                        name = _terminal_name(arg)
                        if name in fns:
                            scheduled.add(name)
                    continue
                callee = _terminal_name(f)
                if callee not in fns:
                    continue
                # Attribute calls on receivers other than self/cls only
                # edge for PRIVATE names: a public method name shared
                # with a builtin ("".join, q.get, t.start) must not
                # stitch unrelated code into the on-loop graph.
                if (
                    isinstance(f, ast.Attribute)
                    and not (
                        isinstance(f.value, ast.Name)
                        and f.value.id in ("self", "cls")
                    )
                    and not callee.startswith("_")
                ):
                    continue
                info.calls.append((callee, node))
            if sink_spans:
                info.calls = [
                    (c, n)
                    for c, n in info.calls
                    if not any(
                        (lo, lc) <= (n.lineno, n.col_offset)
                        and (
                            n.end_lineno or n.lineno,
                            n.end_col_offset or 0,
                        ) <= (hi, hc)
                        for lo, lc, hi, hc in sink_spans
                    )
                ]
        return fns, scheduled

    def _reachable(
        self, src: ModuleSource, fns: Dict[str, _FnInfo], scheduled: Set[str]
    ) -> Dict[str, List[str]]:
        """On-loop reachable function names -> the root→…→fn path that
        proves it (for the finding message)."""
        entry = config.LOOP_ENTRY.get(src.path, ())
        roots = [
            name
            for name, info in fns.items()
            if info.is_async or name in scheduled or name in entry
        ]
        paths: Dict[str, List[str]] = {}
        queue: List[str] = []
        for r in sorted(roots):
            if r in config.OFF_LOOP_HELPERS:
                continue
            paths[r] = [r]
            queue.append(r)
        while queue:
            cur = queue.pop(0)
            for callee, _node in fns[cur].calls:
                if callee in paths or callee in config.OFF_LOOP_HELPERS:
                    continue
                paths[callee] = paths[cur] + [callee]
                queue.append(callee)
        return paths

    # -- blocking catalog ------------------------------------------------------

    def _blocking_ops(
        self,
        src: ModuleSource,
        fn: ast.AST,
        device_fns: Set[str],
        device_methods: Set[str],
        local_fns: Set[str],
    ) -> Iterator[Tuple[ast.AST, ast.stmt, str]]:
        """(node, anchor statement, message) for every blocking op in
        one function body — statement-ordered walk so the host-sync
        taint env is correct at each use."""
        taint = _Taint(device_fns, device_methods, local_fns)
        _seed_params(taint, fn)
        yield from self._walk(src, fn.body, taint)

    def _walk(
        self, src: ModuleSource, body: Sequence[ast.stmt], taint: _Taint
    ) -> Iterator[Tuple[ast.AST, ast.stmt, str]]:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # separate call-graph entries
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                roots: List[ast.AST] = [stmt.iter]
            elif isinstance(stmt, (ast.If, ast.While)):
                roots = [stmt.test]
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                roots = [i.context_expr for i in stmt.items]
            elif isinstance(stmt, ast.Try):
                roots = []
            else:
                roots = [stmt]
            for root in roots:
                yield from self._check_expr(src, root, stmt, taint)
            if isinstance(stmt, ast.Assign):
                v = taint.tainted(stmt.value)
                for t in stmt.targets:
                    taint.bind(t, v)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                taint.bind(stmt.target, taint.tainted(stmt.value))
            elif isinstance(stmt, ast.AugAssign):
                if taint.tainted(stmt.value):
                    taint.bind(stmt.target, True)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                taint.bind(stmt.target, taint.tainted(stmt.iter))
                yield from self._walk(src, stmt.body, taint)
                yield from self._walk(src, stmt.orelse, taint)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from self._walk(src, stmt.body, taint)
            elif isinstance(stmt, (ast.If, ast.While)):
                yield from self._walk(src, stmt.body, taint)
                yield from self._walk(src, stmt.orelse, taint)
            elif isinstance(stmt, ast.Try):
                for blk in (stmt.body, stmt.orelse, stmt.finalbody):
                    yield from self._walk(src, blk, taint)
                for h in stmt.handlers:
                    yield from self._walk(src, h.body, taint)

    def _check_expr(
        self,
        src: ModuleSource,
        root: ast.AST,
        stmt: ast.stmt,
        taint: _Taint,
    ) -> Iterator[Tuple[ast.AST, ast.stmt, str]]:
        for node in ast.walk(root):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.Attribute):
                if node.attr == "block_until_ready":
                    yield (
                        node,
                        stmt,
                        "block_until_ready is a device sync barrier on "
                        "the event loop",
                    )
                continue
            if not isinstance(node, ast.Call):
                continue
            if self._is_sink_call(node):
                continue  # args run off-loop (their OWN defs walk alone)
            f = node.func
            fname = _terminal_name(f)
            # Direct call to a declared off-loop half.
            if fname in config.OFF_LOOP_HELPERS:
                yield (
                    node,
                    stmt,
                    f"off-loop helper {fname}() called synchronously on "
                    "the event loop — route it through run_in_executor "
                    "(the scan_transfer/read_transfer split)",
                )
                continue
            # time.sleep (asyncio.sleep is awaited and fine).
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "sleep"
                and _terminal_name(f.value) == "time"
            ):
                yield (
                    node,
                    stmt,
                    "time.sleep blocks the event loop — use "
                    "await asyncio.sleep",
                )
                continue
            # Sync file IO.
            if isinstance(f, ast.Name) and f.id == "open":
                yield (
                    node,
                    stmt,
                    "sync file open() on the event loop — move the IO "
                    "to run_in_executor",
                )
                continue
            if (
                isinstance(f, ast.Attribute)
                and f.attr in _SYNC_FILE_ATTRS
            ):
                yield (
                    node,
                    stmt,
                    f".{f.attr}() is sync file IO on the event loop — "
                    "move it to run_in_executor",
                )
                continue
            # Subprocess.
            if (
                isinstance(f, ast.Attribute)
                and f.attr in _SUBPROCESS_ATTRS
                and _terminal_name(f.value) == "subprocess"
            ):
                yield (
                    node,
                    stmt,
                    f"subprocess.{f.attr} blocks the event loop",
                )
                continue
            # Sync socket ops (module-level connects and the classic
            # recv/accept/sendall shapes on an explicit socket).
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "create_connection"
                and _terminal_name(f.value) == "socket"
            ):
                yield (
                    node,
                    stmt,
                    "socket.create_connection is a sync connect on the "
                    "event loop",
                )
                continue
            if (
                isinstance(f, ast.Attribute)
                and f.attr in _SYNC_SOCKET_ATTRS
                and "sock" in _terminal_name(f.value).lower()
            ):
                yield (
                    node,
                    stmt,
                    f"sync socket .{f.attr}() on the event loop — use "
                    "the asyncio stream/transport API",
                )
                continue
            # Bounded waits built on select() block the loop for their
            # full timeout.
            if (
                isinstance(f, ast.Attribute)
                and f.attr in ("select", "poll")
                and _terminal_name(f.value) == "select"
            ):
                yield (
                    node,
                    stmt,
                    "select.select blocks the event loop for its "
                    "timeout — use the loop's own readiness machinery",
                )
                continue
            # Unbounded lock acquire (with-statement holds are fine —
            # the lock-order pass audits those; a bare .acquire() with
            # no timeout can park the loop behind any producer thread).
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "acquire"
                and _is_lockish(f.value)
                and not node.args
                and not any(
                    kw.arg in ("timeout", "blocking")
                    for kw in node.keywords
                )
            ):
                yield (
                    node,
                    stmt,
                    "unbounded Lock.acquire() on the event loop — pass "
                    "a timeout or restructure around the loop",
                )
                continue
            # Device→host transfers (the host-sync taint rules).
            if isinstance(f, ast.Attribute) and f.attr == "item":
                yield (
                    node,
                    stmt,
                    ".item() is a blocking per-scalar device readback "
                    "on the event loop",
                )
                continue
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "device_get"
                and _terminal_name(f.value) == "jax"
            ):
                yield (
                    node,
                    stmt,
                    "jax.device_get is a blocking device→host transfer "
                    "on the event loop",
                )
                continue
            if isinstance(f, ast.Attribute) and f.attr == "tolist":
                if taint.tainted(f.value):
                    yield (
                        node,
                        stmt,
                        ".tolist() on a device value is a blocking "
                        "device→host transfer on the event loop",
                    )
                continue
            if _is_np(f, ("asarray", "array")):
                if node.args and taint.tainted(node.args[0]):
                    yield (
                        node,
                        stmt,
                        f"np.{f.attr}({ast.unparse(node.args[0])}) is a "
                        "blocking device→host transfer on the event "
                        "loop — run it in the executor (the "
                        "scan_transfer/read_transfer split)",
                    )
                continue
            if (
                isinstance(f, ast.Name)
                and f.id in ("int", "float", "bool")
                and len(node.args) == 1
                and taint.tainted(node.args[0])
            ):
                yield (
                    node,
                    stmt,
                    f"{f.id}({ast.unparse(node.args[0])}) scalarizes a "
                    "device value on the event loop (one blocking "
                    "transfer per call)",
                )

    # -- pass entry ------------------------------------------------------------

    def run(self, src: ModuleSource) -> Iterator[Tuple[Finding, ast.AST]]:
        fns = self._collect_fns(src.tree)
        fns, scheduled = self._edges_and_roots(fns)
        paths = self._reachable(src, fns, scheduled)
        device_fns = device_fn_names(src.tree)
        device_methods, local_fns = device_method_names(
            src.tree, device_fns
        )
        for name in sorted(paths):
            info = fns[name]
            chain = paths[name]
            via = (
                " (on-loop via " + " -> ".join(chain) + ")"
                if len(chain) > 1
                else ""
            )
            for node, stmt, msg in self._blocking_ops(
                src, info.node, device_fns, device_methods, local_fns
            ):
                yield (
                    src.finding(
                        self.id,
                        node,
                        msg
                        + via
                        + " — annotate `# graftlint: onloop(<reason>)` "
                        "if this on-loop block is audited and "
                        "intentional",
                    ),
                    stmt,
                )
