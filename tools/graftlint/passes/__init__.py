"""Pass registry. Each pass exposes ``id``, ``scope(root)`` (the
repo-relative files it covers), and ``run(src)`` yielding
``(Finding, flagged_node)`` pairs — the node carries the statement span
pragma suppression checks against. A pass may additionally expose
``finalize()`` returning plain findings that need the WHOLE scope
scanned first (cross-file lock-order cycles, dead vocabulary entries);
the runner calls it after the file loop, and skips it under a paths
filter (partial scans cannot prove an entry dead)."""

from tools.graftlint.passes.determinism import DeterminismPass
from tools.graftlint.passes.fault_site import FaultSitePass
from tools.graftlint.passes.host_sync import HostSyncPass
from tools.graftlint.passes.lock_order import LockOrderPass
from tools.graftlint.passes.loop_blocking import LoopBlockingPass
from tools.graftlint.passes.recompile import RecompileHazardPass
from tools.graftlint.passes.vocab_drift import VocabDriftPass
from tools.graftlint.passes.wire_drift import WireDriftPass

ALL_PASSES = (
    HostSyncPass(),
    RecompileHazardPass(),
    DeterminismPass(),
    FaultSitePass(),
    WireDriftPass(),
    LoopBlockingPass(),
    LockOrderPass(),
    VocabDriftPass(),
)

__all__ = [
    "ALL_PASSES",
    "DeterminismPass",
    "FaultSitePass",
    "HostSyncPass",
    "LockOrderPass",
    "LoopBlockingPass",
    "RecompileHazardPass",
    "VocabDriftPass",
    "WireDriftPass",
]
