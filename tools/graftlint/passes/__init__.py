"""Pass registry. Each pass exposes ``id``, ``scope(root)`` (the
repo-relative files it covers), and ``run(src)`` yielding
``(Finding, flagged_node)`` pairs — the node carries the statement span
pragma suppression checks against."""

from tools.graftlint.passes.determinism import DeterminismPass
from tools.graftlint.passes.fault_site import FaultSitePass
from tools.graftlint.passes.host_sync import HostSyncPass
from tools.graftlint.passes.recompile import RecompileHazardPass
from tools.graftlint.passes.wire_drift import WireDriftPass

ALL_PASSES = (
    HostSyncPass(),
    RecompileHazardPass(),
    DeterminismPass(),
    FaultSitePass(),
    WireDriftPass(),
)

__all__ = [
    "ALL_PASSES",
    "DeterminismPass",
    "FaultSitePass",
    "HostSyncPass",
    "RecompileHazardPass",
    "WireDriftPass",
]
