"""Shared graftlint infrastructure: findings, sources, pragmas, baseline,
and the pass runner.

Pragma syntax (inline suppression with a MANDATORY reason)::

    x = np.asarray(dev)  # graftlint: readback(scribe transfer wait)

    # graftlint: nondet(identity membership only; order never observed)
    dropped_ids = {id(op) for op in dropped}

A pragma suppresses findings of its rule on its own physical line, on any
line of the flagged statement's span, or — for a comment-only line — on
the statement that starts on the next line. A pragma with no reason is
itself a finding: the whole point is that every suppression documents WHY
the contract is intentionally bent.
"""

from __future__ import annotations

import ast
import fnmatch
import io
import json
import os
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from tools.graftlint import config

# rule id -> pragma name that suppresses it (wire-drift has no pragma: the
# lock file + version bump is its acceptance mechanism).
PRAGMA_OF_RULE = {
    "host-sync": "readback",
    "recompile-hazard": "recompile",
    "determinism": "nondet",
}
KNOWN_PRAGMAS = frozenset(PRAGMA_OF_RULE.values())


@dataclass(frozen=True)
class Finding:
    rule: str  # pass id ("host-sync", "determinism", ...)
    path: str  # repo-relative POSIX path
    line: int
    col: int
    message: str
    source_line: str = ""  # stripped text at `line` (baseline key)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def baseline_key(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "source_line": self.source_line,
        }


@dataclass
class Pragma:
    line: int
    name: str
    reason: str
    comment_only: bool  # pragma sits on a comment-only line


@dataclass
class ModuleSource:
    """One parsed source file plus its pragma table."""

    path: str  # repo-relative POSIX
    abspath: str
    text: str
    tree: ast.AST
    lines: List[str] = field(default_factory=list)
    pragmas: List[Pragma] = field(default_factory=list)

    @classmethod
    def load(cls, root: str, relpath: str) -> "ModuleSource":
        abspath = os.path.join(root, relpath)
        with open(abspath, encoding="utf-8") as f:
            text = f.read()
        src = cls(
            path=relpath.replace(os.sep, "/"),
            abspath=abspath,
            text=text,
            tree=ast.parse(text, filename=relpath),
            lines=text.splitlines(),
        )
        src.pragmas = _collect_pragmas(text)
        return src

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(
        self, rule: str, node: ast.AST, message: str
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0) + 1
        return Finding(
            rule=rule,
            path=self.path,
            line=line,
            col=col,
            message=message,
            source_line=self.line_text(line),
        )

    def suppressed(self, finding: Finding, node: ast.AST) -> bool:
        """True when a reasoned pragma of the finding's rule covers the
        node's statement span."""
        name = PRAGMA_OF_RULE.get(finding.rule)
        if name is None:
            return False
        lo = getattr(node, "lineno", finding.line)
        hi = getattr(node, "end_lineno", lo) or lo
        for p in self.pragmas:
            if p.name != name or not p.reason.strip():
                continue
            if lo <= p.line <= hi:
                return True
            if p.comment_only and p.line == lo - 1:
                return True
        return False


def _collect_pragmas(text: str) -> List[Pragma]:
    """Pragmas via the tokenizer (a ``# graftlint:`` inside a string
    literal is not a pragma)."""
    out: List[Pragma] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(text).readline))
    except tokenize.TokenError:  # pragma: no cover - unparsable source
        return out
    code_lines = set()
    for tok in tokens:
        if tok.type in (
            tokenize.COMMENT,
            tokenize.NL,
            tokenize.NEWLINE,
            tokenize.INDENT,
            tokenize.DEDENT,
            tokenize.ENDMARKER,
        ):
            continue
        for ln in range(tok.start[0], tok.end[0] + 1):
            code_lines.add(ln)
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        body = tok.string.lstrip("#").strip()
        if not body.startswith("graftlint:"):
            continue
        spec = body[len("graftlint:"):].strip()
        name, _, rest = spec.partition("(")
        reason = rest[:-1] if rest.endswith(")") else rest
        out.append(
            Pragma(
                line=tok.start[0],
                name=name.strip(),
                reason=reason.strip(),
                comment_only=tok.start[0] not in code_lines,
            )
        )
    return out


def pragma_findings(src: ModuleSource) -> List[Finding]:
    """Malformed pragmas are findings themselves: unknown names (typos
    silently suppress nothing) and missing reasons (undocumented
    suppressions defeat the audit trail)."""
    out: List[Finding] = []
    for p in src.pragmas:
        if p.name not in KNOWN_PRAGMAS:
            out.append(
                Finding(
                    rule="pragma",
                    path=src.path,
                    line=p.line,
                    col=1,
                    message=(
                        f"unknown graftlint pragma {p.name!r} "
                        f"(known: {', '.join(sorted(KNOWN_PRAGMAS))})"
                    ),
                    source_line=src.line_text(p.line),
                )
            )
        elif not p.reason.strip():
            out.append(
                Finding(
                    rule="pragma",
                    path=src.path,
                    line=p.line,
                    col=1,
                    message=(
                        f"graftlint pragma {p.name!r} has no reason — "
                        f"write `# graftlint: {p.name}(<why this is "
                        "intentional>)`"
                    ),
                    source_line=src.line_text(p.line),
                )
            )
    return out


# -- scope resolution ----------------------------------------------------------


_SKIP_DIRS = frozenset({".git", "__pycache__", ".claude", "node_modules"})


def scope_files(root: str, patterns: Sequence[str]) -> List[str]:
    """Repo-relative files matching any scope glob, sorted for stable
    output order. Walks the whole repo (pruning VCS/cache dirs) so scope
    patterns outside the package match too — a CI gate whose scope
    silently matched nothing would report clean while covering nothing."""
    out = set()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, fn), root)
            rel = rel.replace(os.sep, "/")
            if any(fnmatch.fnmatch(rel, pat) for pat in patterns):
                out.add(rel)
    return sorted(out)


# -- baseline ------------------------------------------------------------------


def load_baseline(root: str) -> List[dict]:
    path = os.path.join(root, config.BASELINE_FILE)
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return json.load(f)


def apply_baseline(
    findings: List[Finding], baseline: List[dict]
) -> Tuple[List[Finding], List[dict]]:
    """(surviving findings, stale baseline entries). A baseline entry
    matches by (rule, path, source line text) so findings survive line
    drift, and each entry suppresses ONE occurrence — a copy-pasted
    duplicate of a baselined line is a NEW finding, not covered. The
    committed baseline must be empty at merge — it exists only to stage
    burn-downs inside a PR."""
    budget: Dict[Tuple[str, str, str], int] = {}
    for e in baseline:
        k = (e["rule"], e["path"], e["source_line"])
        budget[k] = budget.get(k, 0) + 1
    survivors = []
    for f in findings:
        k = (f.rule, f.path, f.source_line)
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            survivors.append(f)
    stale = []
    for e in baseline:
        k = (e["rule"], e["path"], e["source_line"])
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            stale.append(e)
    return survivors, stale


# -- runner --------------------------------------------------------------------


def run(
    root: Optional[str] = None,
    passes: Optional[Iterable[str]] = None,
    paths: Optional[Sequence[str]] = None,
    use_baseline: bool = True,
) -> Tuple[List[Finding], List[dict]]:
    """Run the selected passes over their configured scopes.

    Returns (findings, stale_baseline_entries). ``paths`` additionally
    filters every pass's scope to the given repo-relative files (fast
    pre-commit loops).
    """
    from tools.graftlint.passes import ALL_PASSES

    root = root or config.REPO_ROOT
    selected = [
        p
        for p in ALL_PASSES
        if passes is None or p.id in set(passes)
    ]
    findings: List[Finding] = []
    seen_files = set()
    src_cache: Dict[str, ModuleSource] = {}

    def get_src(rel: str) -> Optional[ModuleSource]:
        if rel not in src_cache:
            try:
                src_cache[rel] = ModuleSource.load(root, rel)
            except (OSError, SyntaxError) as e:
                src_cache[rel] = None  # type: ignore[assignment]
                findings.append(
                    Finding(
                        rule="parse",
                        path=rel,
                        line=1,
                        col=1,
                        message=f"cannot analyze: {e}",
                    )
                )
        return src_cache[rel]

    for p in selected:
        for rel in p.scope(root):
            if paths and rel not in paths:
                continue
            src = get_src(rel)
            if src is None:
                continue
            if rel not in seen_files:
                seen_files.add(rel)
                findings.extend(pragma_findings(src))
            for f, node in p.run(src):
                if not src.suppressed(f, node):
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    if use_baseline:
        return apply_baseline(findings, load_baseline(root))
    return findings, []
