"""Shared graftlint infrastructure: findings, sources, pragmas, baseline,
and the pass runner.

Pragma syntax (inline suppression with a MANDATORY reason)::

    x = np.asarray(dev)  # graftlint: readback(scribe transfer wait)

    # graftlint: nondet(identity membership only; order never observed)
    dropped_ids = {id(op) for op in dropped}

A pragma suppresses findings of its rule on its own physical line, on any
line of the flagged statement's span, or — for a comment-only line — on
the statement that starts on the next line. A pragma with no reason is
itself a finding: the whole point is that every suppression documents WHY
the contract is intentionally bent.
"""

from __future__ import annotations

import ast
import fnmatch
import io
import json
import os
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from tools.graftlint import config

# rule id -> pragma name that suppresses it (wire-drift has no pragma: the
# lock file + version bump is its acceptance mechanism; fault-site and
# vocab-drift likewise accept by vocabulary declaration).
PRAGMA_OF_RULE = {
    "host-sync": "readback",
    "recompile-hazard": "recompile",
    "determinism": "nondet",
    "loop-blocking": "onloop",
    "lock-order": "lockorder",
}
KNOWN_PRAGMAS = frozenset(PRAGMA_OF_RULE.values())
RULE_OF_PRAGMA = {v: k for k, v in PRAGMA_OF_RULE.items()}


@dataclass(frozen=True)
class Finding:
    rule: str  # pass id ("host-sync", "determinism", ...)
    path: str  # repo-relative POSIX path
    line: int
    col: int
    message: str
    source_line: str = ""  # stripped text at `line` (baseline key)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def baseline_key(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "source_line": self.source_line,
        }


@dataclass
class Pragma:
    line: int
    name: str
    reason: str
    comment_only: bool  # pragma sits on a comment-only line
    used: bool = False  # suppressed at least one finding this run


@dataclass
class ModuleSource:
    """One parsed source file plus its pragma table."""

    path: str  # repo-relative POSIX
    abspath: str
    text: str
    tree: ast.AST
    lines: List[str] = field(default_factory=list)
    pragmas: List[Pragma] = field(default_factory=list)

    @classmethod
    def load(cls, root: str, relpath: str) -> "ModuleSource":
        abspath = os.path.join(root, relpath)
        with open(abspath, encoding="utf-8") as f:
            text = f.read()
        src = cls(
            path=relpath.replace(os.sep, "/"),
            abspath=abspath,
            text=text,
            tree=ast.parse(text, filename=relpath),
            lines=text.splitlines(),
        )
        src.pragmas = _collect_pragmas(text)
        return src

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(
        self, rule: str, node: ast.AST, message: str
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0) + 1
        return Finding(
            rule=rule,
            path=self.path,
            line=line,
            col=col,
            message=message,
            source_line=self.line_text(line),
        )

    def suppressed(self, finding: Finding, node: ast.AST) -> bool:
        """True when a reasoned pragma of the finding's rule covers the
        node's statement span. Marks the matching pragma USED — the
        stale-pragma check reports reasoned pragmas whose finding no
        longer fires, so the audited-exception set can only shrink."""
        name = PRAGMA_OF_RULE.get(finding.rule)
        if name is None:
            return False
        lo = getattr(node, "lineno", finding.line)
        hi = getattr(node, "end_lineno", lo) or lo
        for p in self.pragmas:
            if p.name != name or not p.reason.strip():
                continue
            if lo <= p.line <= hi or (p.comment_only and p.line == lo - 1):
                p.used = True
                return True
        return False


def _collect_pragmas(text: str) -> List[Pragma]:
    """Pragmas via the tokenizer (a ``# graftlint:`` inside a string
    literal is not a pragma)."""
    out: List[Pragma] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(text).readline))
    except tokenize.TokenError:  # pragma: no cover - unparsable source
        return out
    code_lines = set()
    for tok in tokens:
        if tok.type in (
            tokenize.COMMENT,
            tokenize.NL,
            tokenize.NEWLINE,
            tokenize.INDENT,
            tokenize.DEDENT,
            tokenize.ENDMARKER,
        ):
            continue
        for ln in range(tok.start[0], tok.end[0] + 1):
            code_lines.add(ln)
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        body = tok.string.lstrip("#").strip()
        if not body.startswith("graftlint:"):
            continue
        spec = body[len("graftlint:"):].strip()
        name, _, rest = spec.partition("(")
        reason = rest[:-1] if rest.endswith(")") else rest
        out.append(
            Pragma(
                line=tok.start[0],
                name=name.strip(),
                reason=reason.strip(),
                comment_only=tok.start[0] not in code_lines,
            )
        )
    return out


def pragma_findings(src: ModuleSource) -> List[Finding]:
    """Malformed pragmas are findings themselves: unknown names (typos
    silently suppress nothing) and missing reasons (undocumented
    suppressions defeat the audit trail)."""
    out: List[Finding] = []
    for p in src.pragmas:
        if p.name not in KNOWN_PRAGMAS:
            out.append(
                Finding(
                    rule="pragma",
                    path=src.path,
                    line=p.line,
                    col=1,
                    message=(
                        f"unknown graftlint pragma {p.name!r} "
                        f"(known: {', '.join(sorted(KNOWN_PRAGMAS))})"
                    ),
                    source_line=src.line_text(p.line),
                )
            )
        elif not p.reason.strip():
            out.append(
                Finding(
                    rule="pragma",
                    path=src.path,
                    line=p.line,
                    col=1,
                    message=(
                        f"graftlint pragma {p.name!r} has no reason — "
                        f"write `# graftlint: {p.name}(<why this is "
                        "intentional>)`"
                    ),
                    source_line=src.line_text(p.line),
                )
            )
    return out


# -- scope resolution ----------------------------------------------------------


_SKIP_DIRS = frozenset({".git", "__pycache__", ".claude", "node_modules"})


def scope_files(root: str, patterns: Sequence[str]) -> List[str]:
    """Repo-relative files matching any scope glob, sorted for stable
    output order. Walks the whole repo (pruning VCS/cache dirs) so scope
    patterns outside the package match too — a CI gate whose scope
    silently matched nothing would report clean while covering nothing."""
    out = set()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, fn), root)
            rel = rel.replace(os.sep, "/")
            if any(fnmatch.fnmatch(rel, pat) for pat in patterns):
                out.add(rel)
    return sorted(out)


# -- baseline ------------------------------------------------------------------


def load_baseline(root: str) -> List[dict]:
    path = os.path.join(root, config.BASELINE_FILE)
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return json.load(f)


def apply_baseline(
    findings: List[Finding], baseline: List[dict]
) -> Tuple[List[Finding], List[dict]]:
    """(surviving findings, stale baseline entries). A baseline entry
    matches by (rule, path, source line text) so findings survive line
    drift, and each entry suppresses ONE occurrence — a copy-pasted
    duplicate of a baselined line is a NEW finding, not covered. The
    committed baseline must be empty at merge — it exists only to stage
    burn-downs inside a PR."""
    budget: Dict[Tuple[str, str, str], int] = {}
    for e in baseline:
        k = (e["rule"], e["path"], e["source_line"])
        budget[k] = budget.get(k, 0) + 1
    survivors = []
    for f in findings:
        k = (f.rule, f.path, f.source_line)
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            survivors.append(f)
    stale = []
    for e in baseline:
        k = (e["rule"], e["path"], e["source_line"])
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            stale.append(e)
    return survivors, stale


# -- runner --------------------------------------------------------------------


def run(
    root: Optional[str] = None,
    passes: Optional[Iterable[str]] = None,
    paths: Optional[Sequence[str]] = None,
    use_baseline: bool = True,
    timings: Optional[Dict[str, float]] = None,
    check_stale_pragmas: bool = True,
) -> Tuple[List[Finding], List[dict]]:
    """Run the selected passes over their configured scopes.

    Returns (findings, stale_baseline_entries). ``paths`` additionally
    filters every pass's scope to the given repo-relative files (fast
    pre-commit loops). Pass a dict as ``timings`` to collect per-pass
    wall seconds (the CI lint job emits them).

    Two post-file checks run after the per-file loop:

    - passes exposing ``finalize()`` contribute whole-scope findings
      (lock-order cycles, dead vocabulary entries) — skipped under a
      ``paths`` filter, where a partial scan cannot prove anything
      about the rest of the scope;
    - the STALE-PRAGMA check: a reasoned pragma whose pass ran over its
      file without it suppressing anything is itself a finding — the
      audited-exception set can only shrink, never silently outlive the
      hazard it excused.
    """
    import time as _time

    from tools.graftlint.passes import ALL_PASSES

    root = root or config.REPO_ROOT
    selected = [
        p
        for p in ALL_PASSES
        if passes is None or p.id in set(passes)
    ]
    selected_ids = {p.id for p in selected}
    findings: List[Finding] = []
    seen_files = set()
    src_cache: Dict[str, ModuleSource] = {}
    # file -> rules whose pass scanned it (the stale check needs to know
    # a pragma's pass actually looked before calling the pragma dead).
    scanned_by: Dict[str, set] = {}

    def get_src(rel: str) -> Optional[ModuleSource]:
        if rel not in src_cache:
            try:
                src_cache[rel] = ModuleSource.load(root, rel)
            except (OSError, SyntaxError) as e:
                src_cache[rel] = None  # type: ignore[assignment]
                findings.append(
                    Finding(
                        rule="parse",
                        path=rel,
                        line=1,
                        col=1,
                        message=f"cannot analyze: {e}",
                    )
                )
        return src_cache[rel]

    for p in selected:
        t0 = _time.perf_counter()
        for rel in p.scope(root):
            if paths and rel not in paths:
                continue
            src = get_src(rel)
            if src is None:
                continue
            if rel not in seen_files:
                seen_files.add(rel)
                findings.extend(pragma_findings(src))
            scanned_by.setdefault(rel, set()).add(p.id)
            for f, node in p.run(src):
                if not src.suppressed(f, node):
                    findings.append(f)
        fin = getattr(p, "finalize", None)
        if fin is not None and not paths:
            findings.extend(fin())
        if timings is not None:
            timings[p.id] = (
                timings.get(p.id, 0.0) + _time.perf_counter() - t0
            )
    if check_stale_pragmas:
        for rel in sorted(seen_files):
            src = src_cache.get(rel)
            if src is None:
                continue
            for p in src.pragmas:
                rule = RULE_OF_PRAGMA.get(p.name)
                if (
                    rule is None
                    or not p.reason.strip()
                    or p.used
                    or rule not in selected_ids
                ):
                    continue
                if rule not in scanned_by.get(rel, ()):  # pass never looked
                    continue
                findings.append(
                    Finding(
                        rule="stale-pragma",
                        path=rel,
                        line=p.line,
                        col=1,
                        message=(
                            f"stale pragma: `# graftlint: {p.name}(…)` "
                            f"suppresses nothing — the {rule} finding it "
                            "excused no longer fires; delete the pragma "
                            "(the reasoned-exception set only shrinks)"
                        ),
                        source_line=src.line_text(p.line),
                    )
                )
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    if use_baseline:
        return apply_baseline(findings, load_baseline(root))
    return findings, []
