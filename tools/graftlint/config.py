"""Per-pass scope and heuristics configuration.

graftlint is deliberately repo-native: the scopes below name THIS
codebase's device paths, merge paths, and codec modules, and the taint
heuristics name its device-state attribute idioms. Generic linters stop
where type information ends; a repo-native one gets to encode what the
repo already promises in its docstrings (``pool.state`` lives on device,
``fluidframework_tpu.ops`` functions return device values, ...).

All paths are repo-root-relative POSIX globs.
"""

from __future__ import annotations

import os

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# Device-path modules (the host-sync + recompile-hazard scope): code that
# sits between the service front door and the kernels, where an
# unannotated device→host transfer is a serving-latency bug.
DEVICE_PATH_SCOPE = (
    "fluidframework_tpu/ops/*.py",
    "fluidframework_tpu/tree/device_*.py",
    "fluidframework_tpu/parallel/*.py",
    "fluidframework_tpu/service/device_backend.py",
    "fluidframework_tpu/service/fleet_service.py",
)

# Merge/sequencing modules (the determinism scope): code every replica
# runs over the sequenced stream — any iteration-order dependence here is
# the bug class that breaks the identical-replica guarantee.
MERGE_PATH_SCOPE = (
    "fluidframework_tpu/tree/*.py",
    "fluidframework_tpu/ops/*.py",
    "fluidframework_tpu/service/sequencer.py",
    "fluidframework_tpu/service/pipeline.py",
    "fluidframework_tpu/runtime/*.py",
    "fluidframework_tpu/models/*.py",
)

# Codec modules (the wire-drift scope): every accreting format ROADMAP
# names — kernel-row field layout, op frames, log values, binary
# snapshots, the tree move wire, and the scribe lane layout.
CODEC_MODULES = (
    "fluidframework_tpu/protocol/constants.py",
    "fluidframework_tpu/protocol/opframe.py",
    "fluidframework_tpu/service/codec.py",
    "fluidframework_tpu/drivers/binary_snapshot.py",
    "fluidframework_tpu/tree/marks.py",
    "fluidframework_tpu/ops/segment_state.py",
)

# Attribute names that denote device-resident state in this codebase
# (``pool.state``, ``self.tables``, ``svc._scalars``, ...). An attribute
# access whose terminal name is in this set taints the expression as a
# device value for the host-sync pass.
DEVICE_ATTRS = frozenset(
    {"state", "tables", "scalars", "_tables", "_scalars", "_scan"}
)

# Imports from these module prefixes are assumed to RETURN device values
# (the kernel entry points: apply_ops_packed, unpack_state, ...).
KERNEL_MODULE_PREFIXES = ("fluidframework_tpu.ops",)

# Functions whose PARAMETERS carry device values by contract: the
# off-loop transfer halves (scan_transfer/read_transfer/
# doc_states_transfer/_telemetry_readback) receive immutable concrete
# device arrays precisely so an async server can run the blocking
# np.asarray off the serving thread. Local taint cannot see through a
# parameter, so the contract is declared here — their readbacks are
# flagged (and pragma-audited) instead of silently under-flagged.
DEVICE_PARAM_FNS = frozenset(
    {
        "scan_transfer",
        "read_transfer",
        "doc_states_transfer",
        "_telemetry_readback",
    }
)

# Fault-injection scope (the fault-site pass): every package module may
# carry ``@inject_fault`` boundaries; the testing/ package (which DEFINES
# the vocabulary) is excluded by the pass itself. Note fnmatch's ``*``
# crosses ``/``, so one glob covers the whole package.
FAULT_SITE_SCOPE = ("fluidframework_tpu/*.py",)
FAULT_VOCAB_MODULE = "fluidframework_tpu/testing/faults.py"

# -- loop-blocking (r17) -------------------------------------------------------

# The asyncio serving tier: modules whose code runs ON the socket event
# loop. network_server owns the loop; the pipeline pump sweep, the
# device backend's feed/flush surface, and the lambda handlers all
# execute inside it (the per-partition single-sequencer discipline the
# reference enforces by convention in its deli/alfred lambdas).
# store_server is thread-per-connection today but is scoped so any
# future async surface is covered from its first commit.
LOOP_SCOPE = (
    "fluidframework_tpu/service/network_server.py",
    "fluidframework_tpu/service/pipeline.py",
    "fluidframework_tpu/service/device_backend.py",
    "fluidframework_tpu/service/store_server.py",
    "fluidframework_tpu/service/lambdas.py",
)

# Cross-module on-loop entry points: functions the event loop calls
# into from ANOTHER module (so the per-module call graph cannot see the
# async caller). network_server's loop invokes the pipeline service
# surface and the device backend's pump/feed/read surface directly; the
# lambda handlers run inside the pipeline pump sweep. Keyed by
# repo-relative path, values are function/method names treated as
# on-loop roots for that module.
LOOP_ENTRY = {
    "fluidframework_tpu/service/pipeline.py": (
        "pump", "connect", "disconnect", "submit", "submit_frame",
        "submit_frames_bulk", "submit_signal", "doc_head", "ops_range",
        "log_entries", "get_deltas", "latest_summary_pointer",
        "flush_device", "_nack_device_errors", "device_text",
        "device_summary", "take_inbox", "take_inbox_raw",
    ),
    "fluidframework_tpu/service/device_backend.py": (
        "enqueue", "enqueue_frame", "flush", "needs_flush",
        "needs_scan_drain", "prefetch_scan", "scan_prefetched",
        "collect_now", "pump_feed", "pump_feed_counted",
        "pump_feed_absorbed", "pump_stage", "pump_dispatch", "pressure",
        "read_start", "read_finish", "publish_metrics", "has_channel",
        "take_errors", "text_from_state", "summary_from_state",
        "dirty_channels",
    ),
    "fluidframework_tpu/service/lambdas.py": (
        "handler", "handler_batch", "_handle", "_handle_frame", "_emit",
        "pump",
    ),
}

# Sanctioned off-loop halves: blocking by DESIGN, invoked only via
# run_in_executor (the scan_transfer/read_transfer splits and the
# /metrics telemetry readback). They are never treated as on-loop
# reachable — but a DIRECT call to one from an on-loop function is
# itself a finding (the split exists precisely so the blocking half
# never runs inline).
OFF_LOOP_HELPERS = frozenset(
    {"scan_transfer", "read_transfer", "_telemetry_readback"}
)

# -- lock-order (r17) ----------------------------------------------------------

# Lock-discipline scope: every module holding a lock another thread can
# contend on — the telemetry rings/registries (scraped from request
# threads) and the service tier (store node request threads, the
# drainer, admission from ticker + submit paths).
LOCK_SCOPE = (
    "fluidframework_tpu/telemetry/*.py",
    "fluidframework_tpu/service/*.py",
)

# Attribute/name suffixes recognized as locks in ``with`` statements and
# ``.acquire()`` calls.
LOCK_NAMES = ("lock", "_lock")

# Render paths: snapshot/exposition functions served to scrape threads.
# Contract (the r16 hardening pattern): snapshot under ONE lock, render
# outside it — acquiring a second lock while holding one in a render
# path is the nested-hold shape that deadlocked /metrics in r16.
RENDER_PATHS = {
    "fluidframework_tpu/telemetry/metrics.py": (
        "render", "snapshot", "samples", "stage_span_summary",
    ),
    "fluidframework_tpu/telemetry/journal.py": ("render", "snapshot"),
    "fluidframework_tpu/telemetry/profiler.py": (
        "render", "chrome_trace", "summarize", "snapshot",
    ),
}

# Calls that acquire a known lock in ANOTHER module (the per-module
# graph cannot see through them): metric observations take the
# per-metric lock, registry registration takes the registry lock, and
# the journal/profiler record paths take their ring locks. Used both
# for cross-module lock-order edges and for the gc-callback /
# signal-handler lock-free contract.
KNOWN_LOCK_CALLS = {
    # method name -> lock id it acquires
    "inc": "telemetry/metrics._Metric._lock",
    "observe": "telemetry/metrics._Metric._lock",
    "counter": "telemetry/metrics.MetricsRegistry._lock",
    "gauge": "telemetry/metrics.MetricsRegistry._lock",
    "histogram": "telemetry/metrics.MetricsRegistry._lock",
}
# record() receivers -> ring lock (journal.record / JOURNAL.record /
# profiler.record / PROFILER.record).
RECORD_LOCKS = {
    "journal": "telemetry/journal.Journal._lock",
    "JOURNAL": "telemetry/journal.Journal._lock",
    "profiler": "telemetry/profiler.Profiler._lock",
    "PROFILER": "telemetry/profiler.Profiler._lock",
}

# -- vocab-drift (r17) ---------------------------------------------------------

# Observability-vocabulary scope: every package module (including
# testing/ — faults.py legitimately journals ``fault.injected``). The
# declared vocabularies live in the modules below; a string used as a
# site/kind/lane/stage/family in scope must appear in its vocabulary,
# and every vocabulary entry must be used (dead entries fail lint).
VOCAB_SCOPE = ("fluidframework_tpu/*.py",)
JOURNAL_VOCAB_MODULE = "fluidframework_tpu/telemetry/journal.py"
PROFILER_VOCAB_MODULE = "fluidframework_tpu/telemetry/profiler.py"
TRACING_VOCAB_MODULE = "fluidframework_tpu/telemetry/tracing.py"
METRICS_VOCAB_MODULE = "fluidframework_tpu/telemetry/metrics.py"

# Vocabulary entries that are DERIVED (synthesized by read surfaces,
# never recorded by a producer) — exempt from the dead-entry check.
DERIVED_LANES = frozenset({"loop_other"})

# Committed artifacts.
WIRE_LOCK_FILE = "api-report/wire_fingerprints.json"
BASELINE_FILE = "tools/graftlint/baseline.json"
