"""Per-pass scope and heuristics configuration.

graftlint is deliberately repo-native: the scopes below name THIS
codebase's device paths, merge paths, and codec modules, and the taint
heuristics name its device-state attribute idioms. Generic linters stop
where type information ends; a repo-native one gets to encode what the
repo already promises in its docstrings (``pool.state`` lives on device,
``fluidframework_tpu.ops`` functions return device values, ...).

All paths are repo-root-relative POSIX globs.
"""

from __future__ import annotations

import os

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# Device-path modules (the host-sync + recompile-hazard scope): code that
# sits between the service front door and the kernels, where an
# unannotated device→host transfer is a serving-latency bug.
DEVICE_PATH_SCOPE = (
    "fluidframework_tpu/ops/*.py",
    "fluidframework_tpu/tree/device_*.py",
    "fluidframework_tpu/parallel/*.py",
    "fluidframework_tpu/service/device_backend.py",
    "fluidframework_tpu/service/fleet_service.py",
)

# Merge/sequencing modules (the determinism scope): code every replica
# runs over the sequenced stream — any iteration-order dependence here is
# the bug class that breaks the identical-replica guarantee.
MERGE_PATH_SCOPE = (
    "fluidframework_tpu/tree/*.py",
    "fluidframework_tpu/ops/*.py",
    "fluidframework_tpu/service/sequencer.py",
    "fluidframework_tpu/service/pipeline.py",
    "fluidframework_tpu/runtime/*.py",
    "fluidframework_tpu/models/*.py",
)

# Codec modules (the wire-drift scope): every accreting format ROADMAP
# names — kernel-row field layout, op frames, log values, binary
# snapshots, the tree move wire, and the scribe lane layout.
CODEC_MODULES = (
    "fluidframework_tpu/protocol/constants.py",
    "fluidframework_tpu/protocol/opframe.py",
    "fluidframework_tpu/service/codec.py",
    "fluidframework_tpu/drivers/binary_snapshot.py",
    "fluidframework_tpu/tree/marks.py",
    "fluidframework_tpu/ops/segment_state.py",
)

# Attribute names that denote device-resident state in this codebase
# (``pool.state``, ``self.tables``, ``svc._scalars``, ...). An attribute
# access whose terminal name is in this set taints the expression as a
# device value for the host-sync pass.
DEVICE_ATTRS = frozenset(
    {"state", "tables", "scalars", "_tables", "_scalars", "_scan"}
)

# Imports from these module prefixes are assumed to RETURN device values
# (the kernel entry points: apply_ops_packed, unpack_state, ...).
KERNEL_MODULE_PREFIXES = ("fluidframework_tpu.ops",)

# Fault-injection scope (the fault-site pass): every package module may
# carry ``@inject_fault`` boundaries; the testing/ package (which DEFINES
# the vocabulary) is excluded by the pass itself. Note fnmatch's ``*``
# crosses ``/``, so one glob covers the whole package.
FAULT_SITE_SCOPE = ("fluidframework_tpu/*.py",)
FAULT_VOCAB_MODULE = "fluidframework_tpu/testing/faults.py"

# Committed artifacts.
WIRE_LOCK_FILE = "api-report/wire_fingerprints.json"
BASELINE_FILE = "tools/graftlint/baseline.json"
