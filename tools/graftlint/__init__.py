"""graftlint — repo-native static analysis for the TPU serving paths.

The paper's contract is twofold: every client deterministically merges a
totally-ordered op stream into identical state, and this repro's merge
hot paths run as batched device kernels. Neither contract is visible to
a generic linter — flake8 cannot know that ``np.asarray(pool.state.err)``
is a device→host transfer on a serving path, that iterating a ``set()``
in a merge module breaks the identical-replica guarantee, or that a
reordered ``struct.pack`` format silently strands every N-1 reader.

graftlint is the AST-based suite that does know. Four passes:

- **host-sync** — implicit device→host transfers in device-path modules
  (``.item()``, ``int()``/``float()``/``bool()`` on device values,
  ``np.asarray``/``np.array`` on jax values, ``block_until_ready``);
  every intentional readback carries ``# graftlint: readback(<reason>)``.
- **recompile-hazard** — ``jax.jit``/``pallas_call`` construction inside
  loops or uncached per-call functions, and Python branches on traced
  values inside jitted functions.
- **determinism** — unordered ``set`` iteration, ``id()``-keyed ordering,
  and ``id()``/``hash()`` sort keys in merge/sequencing modules.
- **wire-drift** — field/layout fingerprints of the codec modules locked
  in ``api-report/wire_fingerprints.json``; a codec change without a
  version bump fails CI.

Run ``python -m tools.graftlint --check`` (the CI gate) or see
``tools/graftlint/README.md``.
"""

from tools.graftlint.core import Finding, run  # noqa: F401

__all__ = ["Finding", "run"]
