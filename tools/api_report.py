"""API-surface report generator (SURVEY §2.8: the reference locks each
package's public surface with API-Extractor `.api.md` files; here one
plaintext report per top-level module, regenerated and diffed by
tests/test_api_report.py so unreviewed surface drift fails CI).

    python tools/api_report.py            # print to stdout
    python tools/api_report.py write      # regenerate api-report/
"""

from __future__ import annotations

import importlib
import inspect
import os
import pkgutil
import sys

REPORT = os.path.join(os.path.dirname(os.path.dirname(__file__)), "api-report")

SKIP_PREFIXES = ("fluidframework_tpu.testing",)  # test utilities, not API


def public_surface() -> str:
    import fluidframework_tpu

    lines = []
    pkg_path = fluidframework_tpu.__path__
    names = sorted(
        m.name
        for m in pkgutil.walk_packages(pkg_path, "fluidframework_tpu.")
    )
    for name in ["fluidframework_tpu"] + names:
        if name.startswith(SKIP_PREFIXES):
            continue
        try:
            mod = importlib.import_module(name)
        except Exception as e:  # pragma: no cover - import errors are drift
            lines.append(f"{name}: IMPORT ERROR {type(e).__name__}")
            continue
        symbols = []
        for attr in sorted(vars(mod)):
            if attr.startswith("_"):
                continue
            obj = getattr(mod, attr)
            if inspect.ismodule(obj):
                continue
            home = getattr(obj, "__module__", name)
            if isinstance(home, str) and not home.startswith(
                "fluidframework_tpu"
            ):
                continue  # re-exported stdlib/third-party
            kind = (
                "class" if inspect.isclass(obj)
                else "def" if callable(obj)
                else "const"
            )
            symbols.append(f"  {kind} {attr}")
        lines.append(f"{name}:")
        lines.extend(symbols)
    return "\n".join(lines) + "\n"


def main() -> None:
    text = public_surface()
    if len(sys.argv) > 1 and sys.argv[1] == "write":
        os.makedirs(REPORT, exist_ok=True)
        with open(os.path.join(REPORT, "fluidframework_tpu.api.txt"), "w") as f:
            f.write(text)
        print("api-report regenerated")
    else:
        print(text, end="")


if __name__ == "__main__":
    main()
