"""CI gate: the round's committed BENCH artifact must carry the
serving-path headline metrics.

VERDICT r5's standing rule — "a headline number that isn't in a committed
artifact doesn't exist" — was violated two rounds running: config 5's and
config 7's numbers lived only in commit messages while ``BENCH_*.json``
captured the kernel microbench alone. ``bench.py`` now runs the serving
benches and merges their keys into the driver headline line; this check
fails the build if the newest committed ``BENCH_r*.json`` (for rounds
after the metrics existed) is missing them, so the regression class is
structurally closed.

    python tools/check_bench_artifact.py [repo_root]
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
from typing import List, Tuple

# The headline keys bench.py merges into the driver line, each with the
# first round whose artifact must carry it (earlier artifacts are the
# historical record, not subject to the gate). The serving trio landed in
# r6; the device-native move-marks fraction (config 3c-moves) in r7; the
# observability pair — the sampled-frame per-stage latency decomposition
# and the per-shard device occupancy lanes from the single-readback
# telemetry scrape — in r9; the continuous-pump pair — parity-pinned pump
# throughput and the measured device idle fraction — in r10; the
# chaos-recovery headline — serving throughput under the standard 1%
# fault mix, parity-asserted — in r11; the continuous-front-door pair —
# streaming-feed throughput (parity-pinned against the quiescence-gated
# flush path on dense + mesh lanes) and the submit→device-commit feed
# latency under continuous feed — in r12; the overload-envelope pair —
# the goodput curve at 0.5x/1x/2x admission capacity (linear-not-cliff
# asserted in-bench, gapless seq runs across every tier transition) and
# the counted load-shedding tier transitions — in r13; the
# flight-recorder pair — the measured journal-on/journal-off serving
# overhead (asserted ≤ 0.05 in-bench) and the per-stage p99 tail next
# to the r9 means — in r14; the read-tier trio — encode-once fan-out
# throughput (asserted ≥ 5× the per-subscriber-encode baseline
# in-bench), the per-subscriber delivery p99 across the 10k-subscriber
# fan-out, and the batched-snapshot-gather amortization (asserted > 1
# under concurrent load) — in r15; the timeline-profiler trio — the
# per-boxcar host tax (p50/p99 of loop_other + host_stage from one
# captured window, the one-dispatch fusion item's justification
# number), the per-lane pump decomposition (coverage ≥ 0.95 and the
# device-idle reconciliation against serving_pump_device_idle_frac
# asserted in-bench), and the loop-stall watchdog's lag gauge — in r16;
# the residency pair — the cold-op wake latency p99 (first parked op to
# slot restored, measured over the million-doc-corpus churn lane,
# parity-pinned against a never-evicted run with zero lost/dup asserted
# in-bench) and the fleet-as-cache hit ratio — in r19.
REQUIRED = (
    ("pipeline_serving_ops_per_sec", 6),
    ("deli_scribe_e2e_ops_per_sec", 6),
    ("fleet_mesh_ops_per_sec", 6),
    ("tree_moves_device_fraction", 7),
    ("serving_stage_spans_ms", 9),
    ("device_shard_occupancy", 9),
    ("serving_pump_ops_per_sec", 10),
    ("serving_pump_device_idle_frac", 10),
    ("fault_recovery_ops_per_sec", 11),
    ("serving_frontdoor_ops_per_sec", 12),
    ("serving_feed_latency_ms", 12),
    ("overload_goodput_curve", 13),
    ("serving_overload_tier_transitions", 13),
    ("journal_overhead_frac", 14),
    ("serving_stage_p99_ms", 14),
    ("serving_read_fanout_ops_per_sec", 15),
    ("serving_read_delivery_p99_ms", 15),
    ("reads_per_device_dispatch", 15),
    ("serving_host_tax_ms", 16),
    ("pump_lane_profile", 16),
    ("event_loop_lag_ms", 16),
    ("residency_wake_p99_ms", 19),
    ("residency_hit_ratio", 19),
)
# Artifacts up to round 5 predate every gated metric.
BASELINE_ROUND = 5


def artifact_records(path: str) -> List[dict]:
    """Every JSON record line captured in the artifact's output tail."""
    with open(path) as f:
        doc = json.load(f)
    records = []
    for line in doc.get("tail", "").splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            continue
    return records


def missing_keys(path: str, rnd: int) -> List[str]:
    merged: dict = {}
    for rec in artifact_records(path):
        merged.update(rec)
    return [k for k, since in REQUIRED if rnd >= since and k not in merged]


def latest_artifact(root: str) -> Tuple[int, str] | None:
    best = None
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = re.match(r"BENCH_r(\d+)\.json$", os.path.basename(path))
        if m:
            rnd = int(m.group(1))
            if best is None or rnd > best[0]:
                best = (rnd, path)
    return best


def check(root: str) -> int:
    found = latest_artifact(root)
    if found is None:
        print("check_bench_artifact: no BENCH_r*.json committed yet — ok")
        return 0
    rnd, path = found
    if rnd <= BASELINE_ROUND:
        print(
            f"check_bench_artifact: newest artifact is r{rnd} "
            f"(pre-dates the gated metrics) — ok"
        )
        return 0
    missing = missing_keys(path, rnd)
    if missing:
        print(
            f"check_bench_artifact: {os.path.basename(path)} is MISSING "
            f"required headline metrics: {', '.join(missing)}.\n"
            "The headline numbers must be driver-captured — bench.py "
            "emits them; a run that lost them is not a valid round "
            "artifact (VERDICT r5 Weak #1/#2)."
        )
        return 1
    print(
        f"check_bench_artifact: {os.path.basename(path)} carries all "
        "required headline metrics — ok"
    )
    return 0


if __name__ == "__main__":
    sys.exit(check(sys.argv[1] if len(sys.argv) > 1 else "."))
