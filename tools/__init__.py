# Repo tooling namespace (makes `python -m tools.graftlint` runnable
# from the repo root, the same way CI invokes it).
